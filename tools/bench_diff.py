"""Compare bench.py JSON payloads across rounds — the perf GATE.

The repo accumulates per-round bench evidence (``BENCH_r0*.json``
trajectory files written by the driver, ``.bench_full.json`` written by
every ``bench.py`` run) but until ISSUE 11 nothing READ them: a
regression only surfaced when a human eyeballed two JSON blobs.  This
tool diffs two payloads metric-by-metric, direction-aware, and can gate
a run:

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py old.json new.json --fail-on-regression 10

Rules of the diff (the PR 6 honesty discipline applies):

- null-when-unmeasured fields are SKIPPED, never treated as 0 — a CPU
  fallback round cannot fake a regression (or an improvement) on a
  TPU-only metric;
- ``telemetry_schema_version`` is checked first: payloads from
  different schemas do not compare (exit 2) unless
  ``--allow-schema-drift``; the bench ``fleet`` block's
  ``fleet_schema_version`` (ISSUE 15) and the ``lint`` block's
  ``lint_schema_version`` (ISSUE 16) are checked the same way;
- direction comes from the metric name (``*_ms``/latency: lower is
  better; throughput/efficiency/MFU: higher is better); metrics with
  unknown direction are reported informationally and never gate;
- both platforms must match (a cpu-vs-tpu pair compares apples to
  oranges; informational only, exit 0, unless --force).

``--fail-on-regression <pct>`` exits 1 when any direction-aware metric
got worse by more than ``pct`` percent — ``tools/tpu_queue_runner.py``
wires this in after its bench step (``MXTPU_BENCH_REGRESSION_PCT``).
The last stdout line is always ``BENCHDIFF {...json...}``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# metric-name suffix -> direction ("up" = higher is better)
_UP_SUFFIXES = ("value", "mfu", "tflops_delivered", "samples_s",
                "img_s", "img_s_overlapped", "tokens_per_sec", "tok_s",
                "tokens_s_chip", "gb_s", "efficiency", "overlap_frac",
                "overlap_efficiency", "speedup", "per_key_speedup",
                "occupancy", "vs_baseline", "weak_scaling_efficiency",
                "projected_efficiency", "proj_eff_8", "proj_eff_256",
                "tokens_per_step_ratio", "tokens_per_dispatch",
                "spec_accept_rate", "kv_capacity_ratio",
                "quant_train_mfu")
_DOWN_SUFFIXES = ("_ms", "p99", "p50", "ttft", "bubble_frac",
                  "pp_bubble_frac", "exposed_ms", "kv_decode_drift")
# config/provenance keys: never compared (a changed knob is not a perf
# regression; the human reads those out of the payload directly)
_SKIP_KEYS = {"telemetry_schema_version", "fleet_schema_version",
              "lint_schema_version", "multiproc_schema_version",
              "batch", "dtype", "data",
              "steps_per_call", "s2d_stem", "n", "rc", "cmd", "tail",
              "time", "cached_at", "dp", "buckets", "epoch",
              "membership_epoch", "transitions", "ranks",
              "slowest_rank", "tp_shards",
              "procs", "world_size", "rpc_retries", "rpc_timeout_s",
              "quant_schema_version", "compute_dtype", "kv_dtype"}


def direction(key):
    leaf = key.rsplit(".", 1)[-1]
    for s in _DOWN_SUFFIXES:
        if leaf.endswith(s):
            return "down"
    for s in _UP_SUFFIXES:
        if leaf == s or leaf.endswith("_" + s) or leaf.endswith(s):
            return "up"
    return None


def load_payload(path):
    """A bench payload: either a raw bench.py JSON, or a driver
    ``BENCH_r*.json`` wrapper (``{"n", "cmd", "rc", "parsed": {...}}``)
    whose ``parsed`` field carries the payload (None when that round's
    line did not parse — nothing to compare)."""
    with open(path, encoding="utf-8") as f:
        d = json.load(f)
    if isinstance(d, dict) and "parsed" in d and "cmd" in d:
        return d.get("parsed")
    return d


def flatten(d, prefix="", out=None):
    """Scalar numeric leaves as {dotted.path: float}; nulls, bools,
    strings and config keys dropped."""
    if out is None:
        out = {}
    if not isinstance(d, dict):
        return out
    for k, v in d.items():
        if k in _SKIP_KEYS:
            continue
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flatten(v, path, out)
        elif isinstance(v, list):
            for i, item in enumerate(v):
                if isinstance(item, dict):
                    flatten(item, f"{path}[{i}]", out)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[path] = float(v)
        # None / str / bool: skipped (null-when-unmeasured honesty)
    return out


def diff_payloads(old, new, threshold_pct):
    """Compare shared measured metrics; returns (rows, regressions)."""
    fo, fn = flatten(old), flatten(new)
    rows = []
    regressions = []
    for key in sorted(set(fo) & set(fn)):
        a, b = fo[key], fn[key]
        d = direction(key)
        if a == 0:
            pct = None if b == 0 else float("inf")
        else:
            pct = (b - a) / abs(a) * 100.0
        worse = None
        if d == "up" and pct is not None:
            worse = -pct
        elif d == "down" and pct is not None:
            worse = pct
        row = {"metric": key, "old": a, "new": b,
               "change_pct": None if pct in (None, float("inf"))
               else round(pct, 2),
               "direction": d}
        if worse is not None and worse > threshold_pct:
            row["regression"] = True
            regressions.append(row)
        rows.append(row)
    return rows, regressions


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two bench.py JSON payloads, direction-aware")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--fail-on-regression", type=float, default=None,
                    metavar="PCT",
                    help="exit 1 when any metric is worse by > PCT%%")
    ap.add_argument("--allow-schema-drift", action="store_true",
                    help="compare across telemetry_schema_version drift")
    ap.add_argument("--force", action="store_true",
                    help="gate even when the platforms differ")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    old, new = load_payload(args.old), load_payload(args.new)
    verdict = {"old": os.path.basename(args.old),
               "new": os.path.basename(args.new)}
    if not isinstance(old, dict) or not isinstance(new, dict):
        verdict.update(status="unparsed",
                       note="one side carries no parsed payload — "
                            "nothing to compare")
        print("BENCHDIFF " + json.dumps(verdict))
        return 0

    vo = old.get("telemetry_schema_version")
    vn = new.get("telemetry_schema_version")
    if vo is not None and vn is not None and vo != vn \
            and not args.allow_schema_drift:
        verdict.update(status="schema_drift", old_schema=vo,
                       new_schema=vn)
        print("BENCHDIFF " + json.dumps(verdict))
        return 2

    # the fleet snapshot schema is versioned the same way (ISSUE 15):
    # payloads whose `fleet` blocks come from different schemas do not
    # compare
    fvo = ((old.get("extra") or {}).get("fleet")
           or {}).get("fleet_schema_version")
    fvn = ((new.get("extra") or {}).get("fleet")
           or {}).get("fleet_schema_version")
    if fvo is not None and fvn is not None and fvo != fvn \
            and not args.allow_schema_drift:
        verdict.update(status="fleet_schema_drift", old_schema=fvo,
                       new_schema=fvn)
        print("BENCHDIFF " + json.dumps(verdict))
        return 2

    # the lint block (ISSUE 16) is versioned the same way: its counts
    # (rules_enabled, findings, suppressions) only compare within one
    # schema
    lvo = ((old.get("extra") or {}).get("lint")
           or {}).get("lint_schema_version")
    lvn = ((new.get("extra") or {}).get("lint")
           or {}).get("lint_schema_version")
    if lvo is not None and lvn is not None and lvo != lvn \
            and not args.allow_schema_drift:
        verdict.update(status="lint_schema_drift", old_schema=lvo,
                       new_schema=lvn)
        print("BENCHDIFF " + json.dumps(verdict))
        return 2

    # the multiproc block (ISSUE 19) is versioned the same way: its
    # recovery costs (coordinator_reinit_ms, sigkill_recover_ms) only
    # compare within one schema
    mvo = ((old.get("extra") or {}).get("multiproc")
           or {}).get("multiproc_schema_version")
    mvn = ((new.get("extra") or {}).get("multiproc")
           or {}).get("multiproc_schema_version")
    if mvo is not None and mvn is not None and mvo != mvn \
            and not args.allow_schema_drift:
        verdict.update(status="multiproc_schema_drift", old_schema=mvo,
                       new_schema=mvn)
        print("BENCHDIFF " + json.dumps(verdict))
        return 2

    # the quant block (ISSUE 20) is versioned the same way: its
    # capacity/drift fields only compare within one schema
    qvo = ((old.get("extra") or {}).get("quant")
           or {}).get("quant_schema_version")
    qvn = ((new.get("extra") or {}).get("quant")
           or {}).get("quant_schema_version")
    if qvo is not None and qvn is not None and qvo != qvn \
            and not args.allow_schema_drift:
        verdict.update(status="quant_schema_drift", old_schema=qvo,
                       new_schema=qvn)
        print("BENCHDIFF " + json.dumps(verdict))
        return 2

    po, pn = old.get("platform"), new.get("platform")
    gate = args.fail_on_regression is not None
    if po != pn and not args.force:
        # cpu-fallback vs tpu rounds: informational only — the honesty
        # rule again (rounds 4/5 were CPU; gating them against round 3's
        # TPU numbers would "detect" a 90% regression that is really a
        # tunnel outage)
        gate = False
        verdict["platform_mismatch"] = [po, pn]

    threshold = args.fail_on_regression if args.fail_on_regression \
        is not None else 10.0
    rows, regressions = diff_payloads(old, new, threshold)
    if not args.quiet:
        for r in rows:
            mark = " REGRESSION" if r.get("regression") else ""
            d = {"up": "^", "down": "v", None: "?"}[r["direction"]]
            print(f"{r['metric']:58s} {d} {r['old']:>12.4g} -> "
                  f"{r['new']:>12.4g}  {r['change_pct']}%{mark}")
    verdict.update(status="ok" if not regressions else "regression",
                   compared=len(rows),
                   regressions=[{k: r[k] for k in
                                 ("metric", "old", "new", "change_pct")}
                                for r in regressions],
                   threshold_pct=threshold, gated=bool(gate))
    print("BENCHDIFF " + json.dumps(verdict))
    return 1 if (gate and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
