#!/usr/bin/env python
"""mxlint — trace-safety + concurrency static analyzer.

    python tools/mxlint.py mxnet_tpu/gluon/model_zoo
    python tools/mxlint.py my_model.py --format=json
    python tools/mxlint.py --list-rules
    python tools/mxlint.py examples/ --write-baseline base.json
    python tools/mxlint.py examples/ --baseline base.json --fail-on-new

Exit codes: 0 clean, 1 violations, 2 usage/IO error. Loads
``mxnet_tpu/lint`` as a standalone package so linting never imports the
framework (or jax) — the tool works in minimal CI images and on trees
that don't import cleanly.
"""
import importlib.util
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_pkg():
    try:
        # installed / repo-root-on-path case: the real package, but only
        # if mxnet_tpu itself is already imported (avoid pulling in jax)
        if "mxnet_tpu" in sys.modules:
            from mxnet_tpu import lint
            return lint
    except ImportError:
        pass
    pkg_dir = os.path.join(ROOT, "mxnet_tpu", "lint")
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu_lint_standalone", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    pkg = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = pkg
    spec.loader.exec_module(pkg)
    return pkg


if __name__ == "__main__":
    import importlib
    lint = _load_lint_pkg()
    cli = importlib.import_module(lint.__name__ + ".cli")
    sys.exit(cli.main())
