"""Data-parallel scaling-efficiency harness (BASELINE metric: >=70%
scaling efficiency 8 -> 256 chips).

Weak scaling: fixed per-device batch, mesh grown 1 -> N devices; ideal is
flat step time, and efficiency(N) = t(1) / t(N). On real hardware the
collective rides ICI and this number is the pod-scaling headline; on the
virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8) the
devices share host cores, so compute time inflates with N — the harness
then reports `collective_overhead_ms` (step minus perfect-compute-scaling
estimate) as the transferable signal and labels the platform honestly.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/scaling_efficiency.py --model mlp --per-device-batch 64
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(name):
    from mxnet_tpu.gluon import nn
    if name == "mlp":
        net = nn.HybridSequential()
        net.add(nn.Dense(1024, activation="relu"),
                nn.Dense(1024, activation="relu"), nn.Dense(10))
        shape = (784,)
    elif name == "resnet18":
        from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
        net = resnet18_v1(classes=100)
        shape = (3, 64, 64)
    else:
        raise SystemExit(f"unknown model {name}")
    return net, shape


def time_mesh(n_dev, model, per_dev_batch, iters, warmup):
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    net, in_shape = build_model(model)
    net.initialize()
    mesh = make_mesh({"dp": n_dev}, devices=jax.devices()[:n_dev])
    trainer = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.1}, mesh=mesh)
    batch = per_dev_batch * n_dev
    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.rand(batch, *in_shape).astype(np.float32))
    label = mx.nd.array(rng.randint(0, 10, batch))
    for _ in range(max(warmup, 1)):       # >=1: the compile must not be timed
        loss = trainer.step(data, label)
    loss.asnumpy()
    iters = max(iters, 1)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(data, label)
    loss.asnumpy()
    dt = (time.perf_counter() - t0) / iters
    return dt, batch


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="mlp", choices=["mlp", "resnet18"])
    ap.add_argument("--per-device-batch", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--project-3d", metavar="SPECS", default=None,
                    help='comma-separated mesh specs ("dp64tp4,'
                         'dp32tp4pp2" or "64x4x1"): print the analytic '
                         "3D projection instead of timing meshes")
    ap.add_argument("--step-ms", type=float, default=None,
                    help="measured single-chip step ms (3D projection "
                         "input)")
    ap.add_argument("--param-bytes", type=float, default=None)
    ap.add_argument("--act-bytes-per-layer", type=float, default=None)
    ap.add_argument("--n-layers", type=int, default=None)
    ap.add_argument("--pp-microbatches", type=int, default=None)
    ap.add_argument("--base-mfu", type=float, default=None,
                    help="measured single-chip MFU -> projected_mfu "
                         "rows")
    args = ap.parse_args()

    if args.project_3d is not None:
        if args.step_ms is None or args.param_bytes is None:
            raise SystemExit("--project-3d needs --step-ms and "
                             "--param-bytes (measured inputs; a "
                             "projection without them would be a guess)")
        from mxnet_tpu.parallel.mesh import MeshConfig
        shapes = [(c.dp, c.tp, c.pp) for c in
                  (MeshConfig.from_spec(s)
                   for s in args.project_3d.split(","))]
        out = project_3d_scaling(
            args.step_ms, args.param_bytes, mesh_shapes=shapes,
            act_bytes_per_layer=args.act_bytes_per_layer,
            n_layers=args.n_layers,
            pp_microbatches=args.pp_microbatches,
            base_mfu=args.base_mfu)
        print("SCALE3DJSON " + json.dumps(out), flush=True)
        return

    import jax
    n_total = len(jax.devices())
    platform = jax.devices()[0].platform
    sizes = [n for n in (1, 2, 4, 8, 16, 32, 64, 128, 256)
             if n <= n_total]
    rows = []
    t1 = None
    for n in sizes:
        dt, batch = time_mesh(n, args.model, args.per_device_batch,
                              args.iters, args.warmup)
        t1 = t1 if t1 is not None else dt
        eff = t1 / dt
        rows.append({"devices": n, "global_batch": batch,
                     "step_ms": round(dt * 1e3, 2),
                     "samples_per_sec": round(batch / dt, 1),
                     "weak_scaling_efficiency": round(eff, 3)})
        print(json.dumps(rows[-1]), flush=True)
    summary = {
        "metric": "dp_weak_scaling",
        "model": args.model,
        "platform": platform,
        "note": ("virtual CPU mesh shares host cores: efficiency here is a "
                 "lower bound dominated by compute contention, not the "
                 "collective (ICI) cost this measures on real pods")
        if platform == "cpu" else "",
        "rows": rows,
    }
    print("SCALEJSON " + json.dumps(summary), flush=True)




# ---------------------------------------------------------------------------
# Analytic ICI projection (VERDICT r3 weak #6): the BASELINE 8->256-chip
# scaling-efficiency metric cannot be MEASURED in a single-chip
# environment, so this models it from first principles and the measured
# single-chip step time — labeled a projection, with every input shown.
# ---------------------------------------------------------------------------

def project_ici_scaling(step_ms_1chip, param_bytes, chips=(8, 64, 256),
                        ici_gbps_per_link=100.0, links=4, overlap=0.7,
                        ici_domain=256, dcn_gbps_per_host=100.0,
                        chips_per_host=4,
                        host_decode_imgs_per_sec=None,
                        per_chip_imgs_per_sec=None,
                        host_core_scale=1.0,
                        host_parallel_efficiency=None,
                        host_thread_slope_img_s=None):
    """Roofline over a TPU pod slice: ICI allreduce + DCN hop + input feed.

    Three terms, each optional past the first (VERDICT r4 weak #6 asked
    for the latter two — the projection's own note called them the real
    risks, and they were unmodeled):

    1. ICI ring allreduce — per step, data parallelism all-reduces
       `param_bytes` of gradients: ring cost = 2*(N-1)/N * bytes over
       `links` ICI links per chip; a fraction `overlap` hides under
       backward compute (XLA overlaps grad-allreduce with the rest of
       backward; 0.7 is conservative vs published TPU DP studies).

    2. DCN hop — when N exceeds `ici_domain` (one slice: 256 for v5e),
       the reduce goes hierarchical: reduce-scatter inside each slice
       over ICI, then a cross-slice allreduce of each host's shard over
       the data-center network.  Each host carries
       param_bytes / hosts_per_slice of the reduced gradient and moves
       2*(S-1)/S of it across its `dcn_gbps_per_host` NIC for S slices.
       DCN transfers cannot hide under the same overlap window (they
       start only after the intra-slice reduce), so they are charged at
       half the ICI overlap fraction.

    3. Input pipeline — weak scaling adds one feeding host per
       `chips_per_host` chips, so host-fed input is a CONSTANT
       throughput cap, not an N-dependent decay: cap = min(1,
       supply / demand) with per-host supply
       host_decode_imgs_per_sec * host_core_scale *
       host_parallel_efficiency and demand
       chips_per_host * per_chip_imgs_per_sec.  `host_core_scale`
       exists because this repo's measured decode ceiling comes from a
       1-core host while real pod hosts have >100 vCPUs — pass the
       core ratio and the input shows in the output.
       `host_parallel_efficiency` de-rates the pure core ratio by the
       decode pool's MEASURED thread scaling (marginal img/s per added
       thread within the host's cores, over the 1-thread img/s —
       `host_thread_slope_img_s` carries the raw slope for the record).
       When the sweep can't measure it (1-core host: every extra thread
       oversubscribes the same core), pass None and the projection
       discloses the linear-scaling assumption instead of silently
       making it.  The device-resident path (`put_epoch`/
       `step_indexed`, measured in bench extras) bypasses the cap
       entirely; both numbers are reported.

    Efficiency(N) = t_compute / (t_compute + exposed_comm), times the
    input cap for the host-fed row.  Weak scaling: per-chip batch fixed,
    compute time constant in N.  Optimizer time is inside the fused step
    (counted in step_ms_1chip).
    """
    out = []
    ici_bw = ici_gbps_per_link * links * 1e9 / 8       # Gbit/s -> B/s
    dcn_bw = dcn_gbps_per_host * 1e9 / 8
    feed_cap = None
    if host_decode_imgs_per_sec and per_chip_imgs_per_sec:
        par_eff = 1.0 if host_parallel_efficiency is None \
            else host_parallel_efficiency
        supply = host_decode_imgs_per_sec * host_core_scale * par_eff
        demand = chips_per_host * per_chip_imgs_per_sec
        feed_cap = min(1.0, supply / demand)
    for n in chips:
        n_slice = min(n, ici_domain)
        ring = 2 * (n_slice - 1) / n_slice * param_bytes
        t_ici_ms = ring / ici_bw * 1e3
        exposed = t_ici_ms * (1 - overlap)
        slices = -(-n // ici_domain)                   # ceil
        t_dcn_ms = 0.0
        if slices > 1:
            hosts_per_slice = max(1, n_slice // chips_per_host)
            shard = param_bytes / hosts_per_slice
            dcn_bytes = 2 * (slices - 1) / slices * shard
            t_dcn_ms = dcn_bytes / dcn_bw * 1e3
            exposed += t_dcn_ms * (1 - overlap / 2)
        eff = step_ms_1chip / (step_ms_1chip + exposed)
        row = {"chips": n, "allreduce_bytes": int(ring),
               "t_comm_ms": round(t_ici_ms, 3),
               "exposed_ms": round(exposed, 3),
               "projected_efficiency": round(eff, 4)}
        if slices > 1:
            row["dcn_slices"] = slices
            row["t_dcn_ms"] = round(t_dcn_ms, 3)
        if feed_cap is not None:
            row["host_fed_efficiency"] = round(eff * feed_cap, 4)
        out.append(row)
    inputs = {"step_ms_1chip": step_ms_1chip,
              "param_bytes": param_bytes,
              "ici_gbps_per_link": ici_gbps_per_link,
              "links_per_chip": links, "overlap_fraction": overlap,
              "ici_domain": ici_domain,
              "dcn_gbps_per_host": dcn_gbps_per_host,
              "chips_per_host": chips_per_host}
    if feed_cap is not None:
        inputs.update({
            "host_decode_imgs_per_sec": host_decode_imgs_per_sec,
            "per_chip_imgs_per_sec": per_chip_imgs_per_sec,
            "host_core_scale": host_core_scale,
            "host_parallel_efficiency": (
                round(host_parallel_efficiency, 4)
                if host_parallel_efficiency is not None
                else "unmeasured: linear core scaling ASSUMED"),
            "input_feed_cap": round(feed_cap, 4)})
        if host_thread_slope_img_s is not None:
            inputs["host_thread_slope_img_s"] = \
                round(host_thread_slope_img_s, 2)
    return {
        "model": ("ring allreduce over ICI + hierarchical DCN hop + "
                  "host input-feed cap, weak scaling"),
        "inputs": inputs,
        "projection": out,
        "note": ("PROJECTION, not a measurement: single-chip environment "
                 "(see MULTICHIP dryrun for correctness of the sharded "
                 "program). v5e: 4 ICI links/chip at ~100 Gbit/s each, "
                 "256-chip ICI domain; DCN charged only past one slice. "
                 "host_fed_efficiency shows the rec-pipeline cap; the "
                 "device-resident put_epoch path sidesteps it."),
    }


# ---------------------------------------------------------------------------
# 3D (dp x tp x pp) projection (ISSUE 11): the flat-dp roofline above
# models one axis; pod-scale training composes three, each with its own
# comm volume and its own place on the step's critical path.
# ---------------------------------------------------------------------------

def project_3d_scaling(step_ms_1chip, param_bytes, mesh_shapes=None,
                       act_bytes_per_layer=None, n_layers=None,
                       pp_microbatches=None, base_mfu=None,
                       ici_gbps_per_link=100.0, links=4, overlap=0.7):
    """Per-mesh-shape efficiency/MFU projection for a v5e-256 pod.

    Three axis terms, charged per step (every input is surfaced in the
    output — PROJECTION, not measurement):

    - **dp** — ring allreduce of this chip's gradient shard: with tp*pp
      model sharding each chip owns ``param_bytes/(tp*pp)``, so the dp
      ring moves ``2*(dp-1)/dp`` of that; a fraction ``overlap`` hides
      under backward (the PR 5 bucket overlap / LHS machinery).
    - **tp** — megatron activation collectives: ~4 allreduce-equivalents
      per layer per step (2 forward, 2 backward) of
      ``act_bytes_per_layer``, each moving ``2*(tp-1)/tp`` of its
      payload; only half the dp overlap fraction is credited — tp
      collectives sit BETWEEN matmuls on the critical path, where the
      scheduler has far less slack.  Zero when tp=1 or the activation
      inputs are not given (disclosed as unmodeled).
    - **pp** — the 1F1B bubble: compute efficiency is multiplied by
      ``1 - (pp-1)/(M+pp-1)`` (``M = pp_microbatches``, default
      ``4*pp``).  Activation hop bytes are negligible next to the grad
      ring and are not charged.

    ``projected_mfu`` rows appear when ``base_mfu`` (the measured
    single-chip MFU) is given: mfu = base_mfu * efficiency.
    """
    if mesh_shapes is None:
        # the v5e-256 cookbook shapes (docs/PARALLELISM.md)
        mesh_shapes = [(256, 1, 1), (64, 4, 1), (32, 8, 1),
                       (32, 4, 2), (16, 4, 4)]
    ici_bw = ici_gbps_per_link * links * 1e9 / 8
    rows = []
    for shape in mesh_shapes:
        dp, tp, pp = (int(x) for x in shape)
        chips = dp * tp * pp
        shard = param_bytes / (tp * pp)
        ring = 2 * (dp - 1) / dp * shard if dp > 1 else 0.0
        t_dp_ms = ring / ici_bw * 1e3
        exposed = t_dp_ms * (1 - overlap)
        t_tp_ms = tp_modeled = None
        if tp > 1 and act_bytes_per_layer and n_layers:
            tp_bytes = 4 * n_layers * act_bytes_per_layer \
                * 2 * (tp - 1) / tp
            t_tp_ms = tp_bytes / ici_bw * 1e3
            exposed += t_tp_ms * (1 - overlap / 2)
            tp_modeled = True
        elif tp > 1:
            tp_modeled = False          # disclosed: term missing
        m = pp_microbatches if pp_microbatches else 4 * pp
        bubble = (pp - 1) / (m + pp - 1) if pp > 1 else 0.0
        comm_eff = step_ms_1chip / (step_ms_1chip + exposed)
        eff = comm_eff * (1 - bubble)
        row = {"mesh": {"dp": dp, "tp": tp, "pp": pp}, "chips": chips,
               "dp_ring_bytes": int(ring),
               "t_dp_ms": round(t_dp_ms, 3),
               "t_tp_ms": None if t_tp_ms is None else round(t_tp_ms, 3),
               "pp_bubble_frac": round(bubble, 4),
               "exposed_ms": round(exposed, 3),
               "projected_efficiency": round(eff, 4)}
        if tp_modeled is False:
            row["tp_term"] = ("UNMODELED: pass act_bytes_per_layer + "
                              "n_layers to charge tp collectives")
        if base_mfu is not None:
            row["projected_mfu"] = round(base_mfu * eff, 4)
        rows.append(row)
    return {
        "model": ("per-axis ICI comm volume (dp grad ring + megatron tp "
                  "activation collectives) x 1F1B bubble fraction, weak "
                  "scaling"),
        "inputs": {"step_ms_1chip": step_ms_1chip,
                   "param_bytes": param_bytes,
                   "act_bytes_per_layer": act_bytes_per_layer,
                   "n_layers": n_layers,
                   "pp_microbatches": pp_microbatches,
                   "base_mfu": base_mfu,
                   "ici_gbps_per_link": ici_gbps_per_link,
                   "links_per_chip": links, "overlap_fraction": overlap},
        "projection": rows,
        "note": ("PROJECTION, not a measurement (single-chip "
                 "environment); correctness of the composed 3D step is "
                 "gated separately (tests/test_mesh3d.py parity suite). "
                 "tp charged at half the dp overlap credit: its "
                 "collectives sit between matmuls on the critical "
                 "path."),
    }

if __name__ == "__main__":
    main()
