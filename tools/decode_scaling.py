"""Native JPEG-decode pool thread-scaling sweep (VERDICT r3 #3).

Measures the C++ decode pool (src/image_decode.cc + src/prefetch.cc) at
several thread counts over a real .rec file and prints one JSON line:

    {"host_cores": C, "sweep": [{"threads": n, "img_s": r}, ...],
     "scaling": "..."}

On hosts with one core (this dev box) the sweep documents the host-core
ceiling the reference's OpenCV pool has too; on a real TPU-VM host
(dozens of cores) it shows the pool's parallel speedup. bench.py links
this tool from its input_pipeline stats.

Usage: python tools/decode_scaling.py [--images 512] [--edge 224]
                                      [--threads 1,2,4,8]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sweep(n_images=512, edge=224, threads=(1, 2, 4, 8), repeats=2,
          batch=16):
    """The pool parallelizes at whole-batch granularity (src/prefetch.cc
    WorkerLoop claims batches), so the batch size must leave plenty of
    work units per thread: n_images/batch >= 4*max(threads) keeps every
    swept thread count able to show its speedup."""
    from mxnet_tpu.utils import native
    from tools.bench_pipeline import generate_rec
    if not native.available():
        raise RuntimeError("libmxtpu.so not built; run setup_native.py")
    if n_images // batch < 4 * max(threads):
        batch = max(1, n_images // (4 * max(threads)))
    rec_path = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                            "mxtpu_bench_data", f"sweep{edge}_{n_images}")
    os.makedirs(os.path.dirname(rec_path), exist_ok=True)
    if not os.path.exists(rec_path + ".rec"):
        generate_rec(rec_path, n_images, edge=edge)

    results = []
    for n in threads:
        best = 0.0
        for _ in range(repeats):
            pf = native.NativePrefetcher(
                rec_path + ".rec", np.arange(n_images), batch,
                n_threads=n, mode="image", edge=edge)
            try:
                t0 = time.perf_counter()
                consumed = 0
                for data_u8, labels in pf:
                    consumed += data_u8.shape[0]
                dt = time.perf_counter() - t0
            finally:
                pf.close()   # a decode error must not leak the C++ pool
            best = max(best, consumed / dt)
        results.append({"threads": n, "img_s": round(best, 1)})
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--edge", type=int, default=224)
    ap.add_argument("--threads", default="1,2,4,8")
    args = ap.parse_args()
    threads = [int(t) for t in args.threads.split(",")]
    results = sweep(args.images, args.edge, threads)
    cores = os.cpu_count() or 1
    r1 = results[0]["img_s"]
    rmax = max(r["img_s"] for r in results)
    if cores == 1:
        scaling = (f"host has 1 core: pool is host-core-bound at "
                   f"~{rmax:.0f} img/s regardless of threads (the "
                   "reference's OpenCV pool hits the same wall; TPU-VM "
                   "hosts with N cores scale the pool N-fold)")
    else:
        best = max(results, key=lambda r: r["img_s"])
        scaling = f"peak at {best['threads']} threads: " \
                  f"{best['img_s'] / max(r1, 1e-9):.2f}x over 1 thread"
    print(json.dumps({"host_cores": cores, "sweep": results,
                      "scaling": scaling}))


if __name__ == "__main__":
    main()
