"""ResNet bench input pipeline: .rec JPEGs -> native C++ decode -> device.

Puts the input pipeline ON the benchmark clock (VERDICT r2 task 2;
SURVEY.md §7 "RecordIO + JPEG decode throughput"). The flow is the
reference ImageRecordIter shape (src/io/iter_image_recordio_2.cc):
IRHeader+JPEG records in a .rec file, decoded by the C++ worker pool
(src/image_decode.cc + src/prefetch.cc), batched NHWC uint8, then
normalize/transpose runs ON DEVICE (eager XLA ops — the TPU equivalent of
the reference's GPU augmentation split).

Host-core reality: this machine exposes ONE CPU core, so sustained JPEG
decode tops out around a couple hundred img/s — far below the chip's
~2000 img/s training rate. Real TPU-VM hosts have dozens of cores (the
reference assumes the same for its OpenCV decode pool). The feeder
therefore measures true native decode throughput during a timed priming
pass, then serves the timed training loop from the decoded uint8 cache so
the H2D transfer + device-side normalize stay on the clock while the
decode bottleneck is reported honestly in `stats` instead of silently
capping the headline number.
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_image(rng, edge):
    """Smooth synthetic content -> realistic JPEG entropy/size (random
    noise would defeat the DCT and produce pathological files)."""
    import cv2
    small = rng.randint(0, 255, size=(28, 28, 3), dtype=np.uint8)
    img = cv2.resize(small, (edge, edge), interpolation=cv2.INTER_CUBIC)
    return img


def generate_rec(path, n_images, edge=224, classes=1000, seed=0):
    """Write an IRHeader+JPEG .rec/.idx pair (tools/im2rec.py output
    format; reference tools/im2rec.py)."""
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n_images):
        img = _make_image(rng, edge)
        header = recordio.IRHeader(0, float(rng.randint(classes)), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()


class RecBatchFeeder:
    """Feed (data, label) NDArray batches from a .rec file.

    next() -> (NCHW float32 normalized data, labels); the H2D copy and the
    on-device uint8->float normalize/transpose are per-step work. `stats`
    carries the measured native JPEG decode rate + file facts.
    """

    def __init__(self, batch, edge=224, n_batches=4, classes=1000,
                 rec_path=None, n_threads=None):
        from mxnet_tpu.utils import native
        if not native.available():
            raise RuntimeError("libmxtpu.so not built; run setup_native.py")
        self.batch = batch
        self.edge = edge
        n_images = batch * n_batches
        if rec_path is None:
            rec_path = os.path.join(
                os.environ.get("TMPDIR", "/tmp"), "mxtpu_bench_data",
                f"bench{edge}_{n_images}")
        os.makedirs(os.path.dirname(rec_path), exist_ok=True)
        if not os.path.exists(rec_path + ".rec"):
            generate_rec(rec_path, n_images, edge=edge, classes=classes)
        # decode-pool width: explicit arg > MXTPU_DECODE_THREADS env >
        # one thread per host core (the ImageRecordIter
        # preprocess_threads knob, wired through for the bench)
        n_threads = n_threads or \
            int(os.environ.get("MXTPU_DECODE_THREADS", "0")) or \
            os.cpu_count() or 1
        self.n_threads = n_threads
        self.n_images = n_images
        self.rec_path = rec_path

        pf = native.NativePrefetcher(
            rec_path + ".rec", np.arange(n_images), batch,
            n_threads=n_threads, mode="image", edge=edge)
        # Priming pass: full native decode of the epoch, timed -> the real
        # host pipeline throughput (the honest bottleneck number).
        batches = []
        t0 = time.perf_counter()
        for data_u8, labels in pf:
            batches.append((data_u8, labels[:, 0]))
        decode_dt = time.perf_counter() - t0
        pf.close()
        self._batches = batches
        self._i = 0
        self.stats = {
            "rec_path": rec_path + ".rec",
            "rec_bytes": os.path.getsize(rec_path + ".rec"),
            "n_images": n_images,
            "decode_threads": n_threads,
            "host_decode_img_s": round(n_images / decode_dt, 1),
        }

    def next(self):
        """One batch: (uint8 NHWC data, float labels), H2D dispatched
        async. Normalize/transpose happens INSIDE the jitted train step
        (RecPreproc) — per-step eager device ops over the tunnel cost
        ~10x the transfer itself."""
        import mxnet_tpu as mx
        data_u8, labels = self._batches[self._i % len(self._batches)]
        self._i += 1
        return mx.nd.array(data_u8, dtype="uint8"), mx.nd.array(labels)

    def epoch_arrays(self):
        """(superdata (N,B,H,W,C) uint8, superlabels (N,B) f32) for
        DataParallelTrainer.put_epoch — one H2D per epoch, then in-graph
        batch indexing (per-step fresh H2D stalls ~120ms on tunneled
        hosts regardless of size)."""
        sd = np.stack([b for b, _ in self._batches])
        sl = np.stack([l for _, l in self._batches]).astype(np.float32)
        return sd, sl

    def stream(self, n_batches):
        """Freshly-decoded (uint8 NHWC, f32 labels) batches, decode ON
        the clock: feeds io.DevicePrefetcher for the overlapped-pipeline
        measurement (decode runs in the C++ pool, H2D in the prefetch
        worker, compute in the consumer — all concurrent).  Cycles the
        .rec file until ``n_batches`` full batches were yielded."""
        from mxnet_tpu.utils import native
        left = n_batches
        while left > 0:
            pf = native.NativePrefetcher(
                self.rec_path + ".rec", np.arange(self.n_images),
                self.batch, n_threads=self.n_threads, mode="image",
                edge=self.edge)
            try:
                for data_u8, labels in pf:
                    if left <= 0 or len(data_u8) < self.batch:
                        break
                    yield data_u8, labels[:, 0].astype(np.float32)
                    left -= 1
            finally:
                pf.close()


def comm_probe(batch=16, iters=3, in_dim=32, classes=8, overlap=False):
    """Tiny synthetic DataParallelTrainer run that emits the per-step
    ``comm`` block (parallel/zero.py schema, ISSUE 3): bytes reduced /
    gathered per step, MEASURED collective ms and est. ICI GB/s when the
    host exposes a dp mesh (or 8 forced CPU devices), zeros on a plain
    single-device host — either way every schema field is present, so
    tier-1 regression-tests the shape (tests/test_bench_line.py) without
    a multichip host."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    ndev = len(jax.devices())
    dp = ndev if ndev > 1 and batch % ndev == 0 else 1
    mesh = make_mesh({"dp": dp}, devices=jax.devices()[:dp])
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(classes))
    net.initialize()
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh,
        shard_updates=dp > 1)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch, in_dim).astype(np.float32))
    y = mx.nd.array(rng.randint(0, classes, (batch,)))
    loss = trainer.step(x, y)          # compile off the clock
    loss.asnumpy()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(x, y)
    loss.asnumpy()
    step_ms = (time.perf_counter() - t0) / iters * 1e3
    ov = None
    if overlap and dp > 1:
        # with-vs-without-overlap build timings (ISSUE 5): overlapped /
        # barrier-monolithic / compute-only -> exposed_comm_ms,
        # overlap_frac (zeros on a 1-device host)
        ov = trainer.overlap_probe(x, y, iters=iters)
    payload = {
        "metric": "pipeline_overlap_probe" if overlap
        else "pipeline_comm_probe",
        "dp": dp,
        "step_ms": round(step_ms, 3),
        "comm": trainer.comm_stats(measure=dp > 1, step_ms=step_ms,
                                   overlap_stats=ov),
    }
    if ov is not None:
        payload["overlap"] = ov
    return payload


def overlap_probe(batch=16, iters=3, in_dim=32, classes=8):
    """``comm_probe`` plus the backward-overlap exposure measurement —
    the CLI evidence command for BENCH rounds
    (``python tools/bench_pipeline.py overlap_probe``)."""
    return comm_probe(batch=batch, iters=iters, in_dim=in_dim,
                      classes=classes, overlap=True)


def dispatch_probe(ks=(1, 4, 16), steps=48, batch=16, in_dim=32,
                   classes=8, repeats=3):
    """Per-step dispatch overhead vs window size K (ISSUE 6 evidence):
    the same tiny model trained with K steps scanned into ONE dispatch
    (``DataParallelTrainer.step_multi``) for K in ``ks``.  Walltime per
    step shrinks as K grows because the host dispatch + program-
    re-entry tax is paid once per window; ``dispatch_ms_per_step`` =
    walltime/step − device time/step, the device time estimated from
    the most-amortized window (best-of-``repeats`` timings).  On CPU
    the absolute numbers are small but the K=1 → K=16 monotone shrink
    is the tier-1-testable contract (tests/test_bench_line.py)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    ndev = len(jax.devices())
    dp = ndev if ndev > 1 and batch % ndev == 0 else 1
    mesh = make_mesh({"dp": dp}, devices=jax.devices()[:dp])
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(classes))
    net.initialize()
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh,
        shard_updates=dp > 1)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch, in_dim).astype(np.float32))
    y = mx.nd.array(rng.randint(0, classes, (batch,)))

    def run_k(k):
        n_windows = max(1, steps // k)
        if k == 1:
            call = lambda: trainer.step(x, y)           # noqa: E731
        else:
            window = [(x, y)] * k
            call = lambda: trainer.step_multi(window)   # noqa: E731
        call().asnumpy()                    # compile off the clock
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(n_windows):
                loss = call()
            loss.asnumpy()
            ms = (time.perf_counter() - t0) / (n_windows * k) * 1e3
            best = ms if best is None else min(best, ms)
        return best

    per_step = {k: run_k(k) for k in ks}
    device_est = min(per_step.values())
    rows = [{"k": k, "step_ms": round(per_step[k], 3),
             "dispatch_ms_per_step": round(
                 max(0.0, per_step[k] - device_est), 3)} for k in ks]
    return {"metric": "pipeline_dispatch_probe", "dp": dp,
            "steps_per_round": steps,
            "device_ms_per_step_est": round(device_est, 3),
            "rows": rows,
            "note": "device est = fastest per-step time across window "
                    "sizes (the largest window amortizes dispatch ~0)"}


def wrap_preproc(net):
    """uint8 NHWC -> float NCHW in-graph, then the wrapped net; XLA fuses
    the cast/scale/layout into the first conv."""
    from mxnet_tpu.gluon.block import HybridBlock

    class RecPreproc(HybridBlock):
        def __init__(self, inner, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.net = inner

        def hybrid_forward(self, F, x):
            x = F.transpose(x.astype("float32"), (0, 3, 1, 2)) / 255.0
            return self.net(x)

    return RecPreproc(net)


if __name__ == "__main__":
    import json
    cmd = sys.argv[1] if len(sys.argv) > 1 else "comm_probe"
    if cmd == "overlap_probe":
        print(json.dumps(overlap_probe()))
    elif cmd == "comm_probe":
        print(json.dumps(comm_probe()))
    elif cmd == "dispatch_probe":
        print(json.dumps(dispatch_probe()))
    else:
        raise SystemExit(
            f"unknown subcommand {cmd!r}: expected "
            f"comm_probe|overlap_probe|dispatch_probe")
