"""One-shot TPU experiment matrix for the ResNet MFU push (round 3).

Times the fused ResNet-50 train step under the layout/stem/batch knobs and
prints one JSON line per configuration. Run ONLY when the tunnel is free
(single TPU client rule — see .claude/skills/verify/SKILL.md).

    python tools/tpu_conv_experiments.py            # full matrix
    MXTPU_EXP_CONFIGS=s2d,nhwc python tools/...     # subset
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_config(name, batch, s2d, layout, iters=20, warmup=3):
    # fresh process-level env for the conv layout knob (read at trace time)
    if layout:
        os.environ["MXTPU_CONV_LAYOUT"] = layout
    else:
        os.environ.pop("MXTPU_CONV_LAYOUT", None)

    import jax
    from bench import _enable_compile_cache
    _enable_compile_cache()   # retries after tunnel hiccups skip recompiles
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer
    from mxnet_tpu import amp

    if jax.devices()[0].platform == "cpu":   # smoke config
        batch, iters, warmup = min(batch, 8), min(iters, 2), 1
    amp.init(target_dtype="bfloat16")
    net = resnet50_v1(s2d_stem=s2d)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = DataParallelTrainer(net, loss_fn, "sgd",
                                  {"learning_rate": 0.1, "momentum": 0.9},
                                  mesh=mesh)
    data = mx.nd.random.uniform(shape=(batch, 3, 224, 224))
    label = mx.nd.zeros((batch,))
    t_c0 = time.perf_counter()
    for _ in range(warmup):
        loss = trainer.step(data, label)
    loss.asnumpy()
    compile_s = time.perf_counter() - t_c0
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(data, label)
    loss.asnumpy()
    dt = time.perf_counter() - t0
    img_s = batch * iters / dt
    out = {"config": name, "batch": batch, "s2d_stem": s2d,
           "platform": jax.devices()[0].platform,
           "conv_layout": layout or "NCHW",
           "img_per_sec": round(img_s, 2),
           "step_ms": round(dt / iters * 1e3, 2),
           "compile_s": round(compile_s, 1)}
    print(json.dumps(out), flush=True)
    return out


MATRIX = {
    "base": dict(batch=128, s2d=False, layout=None),
    "s2d": dict(batch=128, s2d=True, layout=None),
    "nhwc": dict(batch=128, s2d=False, layout="NHWC"),
    "s2d_nhwc": dict(batch=128, s2d=True, layout="NHWC"),
    "b256": dict(batch=256, s2d=False, layout=None),
    "b256_s2d": dict(batch=256, s2d=True, layout=None),
    "b256_s2d_nhwc": dict(batch=256, s2d=True, layout="NHWC"),
}


def main():
    import subprocess
    child = os.environ.get("MXTPU_EXP_CHILD")
    if child:   # child process: run exactly ONE config, never recurse
        run_config(child, **MATRIX[child])
        return
    want = os.environ.get("MXTPU_EXP_CONFIGS")
    names = want.split(",") if want else list(MATRIX)
    results = []
    for n in names:
        # each config in a subprocess: conv-layout env is baked into traces
        # and jit caches must not leak across configs
        env = dict(os.environ, MXTPU_EXP_CHILD=n)
        try:
            p = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=1800)
            line = [l for l in p.stdout.splitlines() if l.startswith("{")]
            err = (p.stderr or "no output")[-300:]
        except subprocess.TimeoutExpired:
            line, err = [], "timeout after 1800s"
        if line:
            results.append(json.loads(line[-1]))
            print(line[-1], flush=True)
        else:
            print(json.dumps({"config": n, "error": err}), flush=True)
    if results:
        best = max(results, key=lambda r: r.get("img_per_sec", 0))
        print(json.dumps({"best": best}), flush=True)


if __name__ == "__main__":
    main()
