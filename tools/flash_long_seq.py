"""Flash attention long-sequence evidence (VERDICT r3 #2).

For L in {2k, 4k, 8k} (bf16, single chip) measures, each config in its
OWN subprocess (an OOM must not wedge the shared TPU client — same
pattern as tpu_conv_experiments.py):

  - flash:  the Pallas streaming kernel (ops/flash_attention.py)
  - scan:   the blockwise lax.scan fallback (same O(L*bk) memory)
  - naive:  materialized softmax(QK^T)V — the O(L^2) score tensor every
            framework pays without a streaming kernel; at large L this
            is the config that dies of RESOURCE_EXHAUSTED while flash
            keeps running, which is the kernel's reason to exist

Per config: wall ms/call and the device peak HBM (jax memory_stats).
Prints one JSON line; the verify-skill runbook feeds the result into
docs/PERFORMANCE.md and bench extras when run on the real chip.

Usage: python tools/flash_long_seq.py [--ls 2048,4096,8192] [--bh 8]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child():
    import math
    import time
    import numpy as np
    import jax
    import jax.numpy as jnp
    from bench import _enable_compile_cache
    _enable_compile_cache()   # retries after tunnel hiccups skip recompiles
    from mxnet_tpu.ops.flash_attention import _flash, _scan_forward

    impl = os.environ["MXTPU_FLASH_IMPL"]
    L = int(os.environ["MXTPU_FLASH_L"])
    bh = int(os.environ.get("MXTPU_FLASH_BH", "8"))
    dhead = int(os.environ.get("MXTPU_FLASH_D", "64"))
    iters = int(os.environ.get("MXTPU_FLASH_ITERS", "5"))
    scale = 1.0 / math.sqrt(dhead)

    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.randn(bh, L, dhead), jnp.bfloat16)
               for _ in range(3))

    if impl == "flash":
        fn = jax.jit(lambda q, k, v: _flash(q, k, v, False, scale))
    elif impl == "scan":
        fn = jax.jit(lambda q, k, v: _scan_forward(
            q, k, v, False, scale, min(256, L))[0])
    else:   # naive: materialized (L, L) scores
        def naive(q, k, v):
            s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
            return jnp.einsum("bqk,bkd->bqd",
                              jax.nn.softmax(s, axis=-1), v)
        fn = jax.jit(naive)

    out = {"impl": impl, "L": L, "platform": jax.devices()[0].platform}
    try:
        fn(q, k, v).block_until_ready()     # compile + first run
        t0 = time.perf_counter()
        for _ in range(iters):
            y = fn(q, k, v)
        y.block_until_ready()
        out["ms"] = round((time.perf_counter() - t0) / iters * 1e3, 2)
        try:
            stats = jax.local_devices()[0].memory_stats()
            out["peak_hbm_gb"] = round(
                stats.get("peak_bytes_in_use", 0) / 1e9, 3)
        except Exception:  # noqa: BLE001 — CPU backend has no stats
            out["peak_hbm_gb"] = None
        out["ok"] = True
    except Exception as e:  # noqa: BLE001 — OOM is a RESULT here
        msg = str(e)
        out["ok"] = False
        out["oom"] = "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg
        out["error"] = msg[:200]
    print("CHILD " + json.dumps(out), flush=True)


def child_env(impl, L, bh=8, base=None, block_q=None, block_kv=None):
    """Env for one (impl, L) child — the single source of the child
    protocol (also used by tools/tpu_queue_runner.py).  ``block_q`` /
    ``block_kv`` pin the Pallas block-size knobs (MXTPU_FLASH_BLOCK_Q/
    KV) for the autotune sweep."""
    env = dict(base if base is not None else os.environ)
    if block_q is not None:
        env["MXTPU_FLASH_BLOCK_Q"] = str(block_q)
    if block_kv is not None:
        env["MXTPU_FLASH_BLOCK_KV"] = str(block_kv)
    env.update({"MXTPU_FLASH_CHILD": "1", "MXTPU_FLASH_IMPL": impl,
                "MXTPU_FLASH_L": str(L), "MXTPU_FLASH_BH": str(bh),
                # prepend REPO, KEEP the ambient path (axon sitecustomize
                # must stay importable for TPU); no empty components — an
                # empty PYTHONPATH element means cwd and can shadow stdlib
                "PYTHONPATH": os.pathsep.join(
                    [REPO] + [p for p in
                              env.get("PYTHONPATH", "").split(os.pathsep)
                              if p])})
    return env


def parse_child_line(text):
    """Extract the child's CHILD-prefixed JSON result, or None."""
    for line in text.splitlines():
        if line.startswith("CHILD "):
            try:
                return json.loads(line[6:])
            except ValueError:
                return None
    return None


def sweep(ls=(2048, 4096, 8192), bh=8, impls=("flash", "scan", "naive")):
    results = []
    for L in ls:
        for impl in impls:
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True, text=True, timeout=900,
                    env=child_env(impl, L, bh))
            except subprocess.TimeoutExpired:
                # a hung config must not discard the results already won
                results.append({"impl": impl, "L": L, "ok": False,
                                "error": "timeout (900s)"})
                continue
            parsed = parse_child_line(r.stdout)
            if parsed is not None:
                results.append(parsed)
            else:
                results.append({"impl": impl, "L": L, "ok": False,
                                "error": (r.stderr or "no output")[-200:]})
    return results


_BLOCK_GRID = ((128, 128), (256, 256), (512, 512), (256, 512),
               (512, 256), (512, 1024), (1024, 512))


def block_sweep(L=2048, bh=8, blocks=_BLOCK_GRID):
    """Autotune the Pallas flash block sizes at sequence length ``L``
    (ISSUE 6 satellite — the 1.03x follow-up): run the flash impl once
    per (BLOCK_Q, BLOCK_KV) candidate, each in its own subprocess with
    ``MXTPU_FLASH_BLOCK_Q/KV`` pinned, and report every timing plus the
    winner — so the TPU re-measure round ships the best measured config
    (bench.py reads it back through .bench_knobs.json flash_bq/flash_bk)
    instead of the untuned default."""
    results = []
    for bq, bkv in blocks:
        if bq > L or bkv > L:
            continue
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=900,
                env=child_env("flash", L, bh, block_q=bq, block_kv=bkv))
        except subprocess.TimeoutExpired:
            results.append({"block_q": bq, "block_kv": bkv, "ok": False,
                            "error": "timeout (900s)"})
            continue
        parsed = parse_child_line(r.stdout)
        if parsed is None:
            parsed = {"ok": False,
                      "error": (r.stderr or "no output")[-200:]}
        parsed.update({"block_q": bq, "block_kv": bkv})
        results.append(parsed)
    timed = [r for r in results if r.get("ok") and "ms" in r]
    best = min(timed, key=lambda r: r["ms"]) if timed else None
    out = {"L": L, "bh": bh, "sweep": results}
    if best is not None:
        out["best"] = {"block_q": best["block_q"],
                       "block_kv": best["block_kv"], "ms": best["ms"]}
        default = next((r for r in timed
                        if r["block_q"] == 512 and r["block_kv"] == 512),
                       None)
        if default is not None and best["ms"] > 0:
            out["best"]["speedup_vs_default"] = round(
                default["ms"] / best["ms"], 3)
    return out


def summarize(results):
    by = {(r["L"], r["impl"]): r for r in results}
    summary = []
    for L in sorted({r["L"] for r in results}):
        f, s, n = by.get((L, "flash")), by.get((L, "scan")), \
            by.get((L, "naive"))
        row = {"L": L}
        if f and f.get("ok"):
            row["flash_ms"] = f["ms"]
            row["flash_peak_hbm_gb"] = f.get("peak_hbm_gb")
        if s and s.get("ok") and f and f.get("ok"):
            row["scan_ms"] = s["ms"]
            row["flash_speedup_vs_scan"] = round(s["ms"] / f["ms"], 2)
        if n:
            row["naive_ok"] = n.get("ok", False)
            if n.get("ok"):
                row["naive_ms"] = n["ms"]
                row["naive_peak_hbm_gb"] = n.get("peak_hbm_gb")
            elif n.get("oom"):
                row["naive_oom"] = True   # the footprint evidence
        summary.append(row)
    return summary


def main():
    if os.environ.get("MXTPU_FLASH_CHILD") == "1":
        _child()
        return
    ap = argparse.ArgumentParser()
    ap.add_argument("--ls", default="2048,4096,8192")
    ap.add_argument("--bh", type=int, default=8)
    ap.add_argument("--impls", default="flash,scan,naive")
    ap.add_argument("--block-sweep", action="store_true",
                    help="autotune MXTPU_FLASH_BLOCK_Q/KV for the flash "
                         "impl at the FIRST --ls length instead of the "
                         "impl sweep")
    args = ap.parse_args()
    ls = tuple(int(x) for x in args.ls.split(","))
    if args.block_sweep:
        print(json.dumps(block_sweep(L=ls[0], bh=args.bh)))
        return
    results = sweep(ls, bh=args.bh, impls=tuple(args.impls.split(",")))
    print(json.dumps({"sweep": results, "summary": summarize(results)}))


if __name__ == "__main__":
    main()
