#!/usr/bin/env python
"""Distributed job launcher (reference: tools/launch.py + dmlc-core tracker).

The reference spawns scheduler/server/worker processes over ssh/mpi/local
and wires them with ``DMLC_*`` env. The TPU-native rebuild has no servers:
each host runs ONE worker process and the processes rendezvous through
``jax.distributed`` (coordinator = worker 0). This launcher keeps the
reference's CLI:

    python tools/launch.py -n 4 --launcher local python train.py --kv-store dist_sync

``--launcher local`` forks N worker processes on this machine (the
reference's fake-cluster mode used by tests/nightly/dist_sync_kvstore.py);
each gets JAX_PLATFORMS=cpu and a private coordinator port so the whole
flow (rendezvous, psum over processes, barrier) runs on one box.
``--launcher ssh`` emits the per-host command lines (zero-egress images
cannot ssh; print instead of exec so the operator's scheduler runs them).

``--supervise`` (ISSUE 19) upgrades the local mode into the real pod
launcher built on :class:`mxnet_tpu.pod.PodLauncher`: children are
watched, a worker death is COMMITTED as a membership change (atomic
``membership.json`` with a fresh coordinator port), and the survivors
tear down + re-init the JAX coordination service at the smaller world
size (``_dist_init.reinit_distributed``) and resume from the shared
checkpoint — a real death changes ``jax.process_count()``.  With no
command given it runs the deterministic ``mxnet_tpu.testing.pod_worker``
workload; the final stdout line is one JSON summary (epoch, dead ranks,
requeued requests).

    python tools/launch.py -n 4 --supervise --pod-dir /tmp/pod --steps 8
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def supervise(args):
    """The pod mode: spawn + supervise through mxnet_tpu.pod, print one
    JSON summary line (what tools/tpu_queue_runner.py parses)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.pod import PodLauncher
    pod_dir = args.pod_dir or tempfile.mkdtemp(prefix="mxtpu_pod_")
    env = {}
    for kv in args.env:
        k, _, v = kv.partition("=")
        env[k] = v
    launcher = PodLauncher(args.num_workers, pod_dir,
                           argv=args.command or None, env=env,
                           steps=args.steps,
                           ckpt_every=args.ckpt_every)
    launcher.start()
    try:
        summary = launcher.supervise(timeout_s=args.timeout)
    finally:
        launcher.shutdown()
    summary["pod_dir"] = pod_dir
    print("PODLAUNCH " + json.dumps(summary))
    return 0 if set(summary["done"]) else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="parameter-server processes for dist_async "
                         "(reference DMLC_NUM_SERVER); keys shard across "
                         "them by crc32. 0 = no server role (dist_sync "
                         "needs none; dist_async then runs one server "
                         "inside worker 0)")
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line (ssh launcher)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE env for workers")
    ap.add_argument("--supervise", action="store_true",
                    help="pod mode (ISSUE 19): watch children, commit "
                         "membership changes on death, survivors "
                         "re-init jax.distributed at the new world")
    ap.add_argument("--pod-dir", default=None,
                    help="control-plane directory for --supervise "
                         "(default: a fresh temp dir)")
    ap.add_argument("--steps", type=int, default=8,
                    help="pod_worker training steps (--supervise)")
    ap.add_argument("--ckpt-every", type=int, default=3,
                    help="pod_worker checkpoint cadence (--supervise)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="supervision deadline in seconds")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if args.supervise:
        return supervise(args)
    if not args.command:
        ap.error("no command given")
    cmd = args.command

    port = _free_port()
    coordinator = f"127.0.0.1:{port}"

    if args.launcher == "ssh":
        hosts = []
        if args.hostfile:
            with open(args.hostfile) as f:
                hosts = [h.strip() for h in f if h.strip()]
        if not hosts:
            hosts = [f"host{i}" for i in range(args.num_workers)]
        coord = f"{hosts[0]}:{port}"
        ps_env = ""
        print("# zero-egress image: run these on each host")
        if args.num_servers > 0:
            addrs = ",".join(
                f"{hosts[s % len(hosts)]}:{port + 1000 + s}"
                for s in range(args.num_servers))
            ps_env = f"MXTPU_PS_ADDRS={addrs} "
            for sid in range(args.num_servers):
                env = (f"DMLC_ROLE=server "
                       f"DMLC_NUM_WORKER={args.num_workers} "
                       f"DMLC_NUM_SERVER={args.num_servers} "
                       f"{ps_env}MXTPU_SERVER_ID={sid} "
                       f"MXTPU_NUM_PROCESSES={args.num_workers}")
                print(f"ssh {hosts[sid % len(hosts)]} '{env} "
                      f"{sys.executable} -m mxnet_tpu.kvstore.ps_server'")
        for rank in range(args.num_workers):
            env = (f"DMLC_ROLE=worker DMLC_NUM_WORKER={args.num_workers} "
                   f"DMLC_NUM_SERVER={args.num_servers} "
                   f"{ps_env}"
                   f"DMLC_WORKER_ID={rank} "
                   f"MXTPU_COORDINATOR={coord} "
                   f"MXTPU_NUM_PROCESSES={args.num_workers} "
                   f"MXTPU_PROCESS_ID={rank}")
            print(f"ssh {hosts[rank % len(hosts)]} '{env} "
                  f"{' '.join(cmd)}'")
        return 0

    ps_addrs = ""
    if args.num_servers > 0:
        ps_addrs = ",".join(f"127.0.0.1:{_free_port()}"
                            for _ in range(args.num_servers))

    procs = []
    try:
        for sid in range(args.num_servers):
            env = dict(os.environ)
            env.update({
                "DMLC_ROLE": "server",
                "DMLC_NUM_WORKER": str(args.num_workers),
                "DMLC_NUM_SERVER": str(args.num_servers),
                "MXTPU_PS_ADDRS": ps_addrs,
                "MXTPU_SERVER_ID": str(sid),
                "MXTPU_NUM_PROCESSES": str(args.num_workers),
                "JAX_PLATFORMS": "cpu",
            })
            for kv in args.env:
                k, _, v = kv.partition("=")
                env[k] = v
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "mxnet_tpu.kvstore.ps_server"],
                env=env))
        workers = []
        for rank in range(args.num_workers):
            env = dict(os.environ)
            env.update({
                "DMLC_ROLE": "worker",
                "DMLC_NUM_WORKER": str(args.num_workers),
                "DMLC_NUM_SERVER": str(args.num_servers),
                "DMLC_WORKER_ID": str(rank),
                "MXTPU_COORDINATOR": coordinator,
                "MXTPU_NUM_PROCESSES": str(args.num_workers),
                "MXTPU_PROCESS_ID": str(rank),
                # local fake cluster runs on CPU (SURVEY.md §4 technique 3)
                "JAX_PLATFORMS": "cpu",
            })
            if ps_addrs:
                env["MXTPU_PS_ADDRS"] = ps_addrs
            for kv in args.env:
                k, _, v = kv.partition("=")
                env[k] = v
            p = subprocess.Popen(cmd, env=env)
            procs.append(p)
            workers.append(p)
        rc = 0
        for p in workers:     # servers serve until torn down below
            rc = p.wait() or rc
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


if __name__ == "__main__":
    sys.exit(main())
