#!/usr/bin/env python
"""Read mx.telemetry state — live or post-mortem — and print it
(ISSUE 9 tooling; fleet aggregation since ISSUE 15).

Sources, one renderer:

  --file PATH        a flight-recorder dump (``mxtpu_flight.<pid>.json``)
                     or a bare ``snapshot()`` JSON file
  --host SPEC        live scrape over the PS server's ``_OP_TELEMETRY``
                     RPC.  SPEC is one or more comma-separated hosts
                     (``h``, ``h:p``, or ``h0:p0,h1:p1,...`` — bare
                     hosts take --port).  A dead host prints ONE typed
                     ``SCRAPE_FAILED {...}`` line and the dump
                     continues with the survivors instead of aborting.
  --fleet            merge the multi-host scrape into ONE fleet
                     snapshot (``telemetry.fleet.FleetCollector``):
                     counters summed, per-rank gauges, histograms
                     merged EXACTLY, skew analysis naming the slowest
                     rank.  With --trace the stitched per-rank span
                     rings export as one perfetto timeline (clock
                     offsets disclosed per lane, never applied).
  --self-test        emit a tiny in-process registry (smoke/demo)
  --trace OUT.json   export the merged causal-tracing + profiler span
                     stream as Chrome-trace JSON (ISSUE 14; with
                     --fleet: the stitched multi-worker timeline)

``--format=prom`` prints Prometheus text exposition (the scrape
integration path); ``--format=json`` prints the snapshot/dump verbatim.
For flight-recorder files, ``--events`` appends the event ring as JSONL
after the metrics.

Examples:
  python tools/telemetry_dump.py --file /tmp/mxtpu_flight.4242.json
  python tools/telemetry_dump.py --host 127.0.0.1 --port 9090 --format=prom
  python tools/telemetry_dump.py --fleet --host h0:9090,h1:9090 --trace pod.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_file(path):
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    # flight dump wraps the snapshot under "metrics"; a bare snapshot
    # has "counters"/"gauges" at top level
    if "metrics" in payload and "counters" not in payload:
        return payload, payload["metrics"]
    return payload, payload


def _parse_hosts(spec, port):
    """``h``, ``h:p``, or a comma-separated list of either -> ordered
    [(host, port), ...]; bare hosts need --port."""
    out = []
    for part in (p.strip() for p in str(spec).split(",") if p.strip()):
        host, _, p = part.rpartition(":")
        if host and p.isdigit():
            out.append((host, int(p)))
        elif port:
            out.append((part, int(port)))
        else:
            raise SystemExit(f"host {part!r} carries no port and no "
                             f"--port was given")
    return out


def _scrape(host, port, fmt):
    from mxnet_tpu.kvstore.ps_server import PSClient
    client = PSClient(host, port, retries=3)
    try:
        return client.telemetry(fmt=fmt)
    finally:
        client.close()


def _dump_hosts(hosts, fmt):
    """Per-host scrape, one section each; a dead host is a typed line,
    not an abort (ISSUE 15 satellite).  Exit 0 when at least one host
    answered."""
    ok = 0
    for host, port in hosts:
        try:
            out = _scrape(host, port, fmt)
        except Exception as e:  # noqa: BLE001 — typed line, keep going
            print("SCRAPE_FAILED " + json.dumps(
                {"host": host, "port": port,
                 "error": f"{type(e).__name__}: {e}"}))
            continue
        ok += 1
        if len(hosts) > 1:
            print(f"# host {host}:{port}")
        if fmt == "prom":
            print(out.get("text", ""), end="")
        else:
            print(json.dumps(out, indent=1))
    return 0 if ok else 1


def _dump_fleet(hosts, fmt, trace_out):
    """Multi-host scrape merged into ONE fleet snapshot; per-host
    failures stay typed lines AND land in the snapshot's per_rank
    rows."""
    from mxnet_tpu.telemetry import fleet as fleet_mod
    transports = {rank: fleet_mod.ps_transport(host, port)
                  for rank, (host, port) in enumerate(hosts)}
    coll = fleet_mod.FleetCollector(transports)
    snap = coll.collect()
    for rank_s, row in sorted((snap.get("per_rank") or {}).items(),
                              key=lambda kv: int(kv[0])):
        if not row.get("ok"):
            host, port = hosts[int(rank_s)]
            print("SCRAPE_FAILED " + json.dumps(
                {"rank": int(rank_s), "host": host, "port": port,
                 "error": row.get("error")}))
    if trace_out:
        from mxnet_tpu.telemetry import tracing
        payload = tracing.chrome_trace(fleet=snap)
        with open(trace_out, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        n = sum(1 for ev in payload["traceEvents"]
                if ev.get("ph") != "M")
        print(f"# wrote {n} fleet trace event(s) to {trace_out}")
        return 0 if snap.get("alive") else 1
    if fmt == "prom":
        from mxnet_tpu.telemetry.prom import prom_text
        print(prom_text(fleet_mod.fleet_prom_snapshot(snap)), end="")
    else:
        # the span rings are trace payload, not a metrics dump — keep
        # the JSON view readable
        slim = dict(snap)
        slim["per_rank"] = {r: {k: v for k, v in row.items()
                                if k != "spans"}
                            for r, row in snap["per_rank"].items()}
        print(json.dumps(slim, indent=1))
    return 0 if snap.get("alive") else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--file", help="flight-recorder dump or snapshot JSON")
    ap.add_argument("--host", help="PS host(s): h, h:p, or a "
                                   "comma-separated list")
    ap.add_argument("--port", type=int, help="default port for bare "
                                             "--host entries")
    ap.add_argument("--format", choices=("prom", "json"), default="prom")
    ap.add_argument("--fleet", action="store_true",
                    help="merge the multi-host scrape into ONE fleet "
                         "snapshot (ISSUE 15)")
    ap.add_argument("--events", action="store_true",
                    help="also print the event ring (flight dumps) as "
                         "JSONL")
    ap.add_argument("--self-test", action="store_true",
                    help="render a tiny in-process registry and exit")
    ap.add_argument("--trace", metavar="OUT",
                    help="write the merged tracing + profiler span "
                         "stream as Chrome-trace JSON to OUT (with "
                         "--fleet: the stitched per-rank timeline)")
    args = ap.parse_args(argv)

    from mxnet_tpu.telemetry.prom import prom_text

    if args.self_test:
        from mxnet_tpu import telemetry
        from mxnet_tpu.telemetry import tracing
        telemetry.inc("selftest.counter", 3)
        telemetry.set_gauge("selftest.gauge", 1.5)
        telemetry.observe("selftest.ms", 2.0)
        with tracing.span("selftest.root", demo=True):
            with tracing.span("selftest.child"):
                pass
        snap = telemetry.snapshot()
        print(prom_text(snap) if args.format == "prom"
              else json.dumps(snap, indent=1))
        if not args.trace:
            return 0

    if args.host:
        hosts = _parse_hosts(args.host, args.port)
        if args.fleet:
            return _dump_fleet(hosts, args.format, args.trace)
        return _dump_hosts(hosts, args.format)

    if args.trace:
        from mxnet_tpu.telemetry import tracing
        payload = tracing.chrome_trace()
        with open(args.trace, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        n = sum(1 for ev in payload["traceEvents"]
                if ev.get("ph") != "M")
        print(f"# wrote {n} trace event(s) to {args.trace}")
        return 0

    if args.file:
        payload, snap = _load_file(args.file)
        if args.format == "json":
            print(json.dumps(payload, indent=1))
        else:
            if payload is not snap and "reason" in payload:
                print(f"# flight dump: reason={payload['reason']!r} "
                      f"pid={payload.get('pid')} "
                      f"t={payload.get('time')}")
            print(prom_text(snap), end="")
        if args.events and payload is not snap:
            for ev in payload.get("events", []):
                print(json.dumps(ev))
        return 0

    ap.error("need --file, --host, or --self-test")
    return 2


if __name__ == "__main__":
    sys.exit(main())
