#!/usr/bin/env python
"""Read mx.telemetry state — live or post-mortem — and print it
(ISSUE 9 tooling).

Three sources, one renderer:

  --file PATH        a flight-recorder dump (``mxtpu_flight.<pid>.json``)
                     or a bare ``snapshot()`` JSON file
  --host H --port P  live scrape over the PS server's ``_OP_TELEMETRY``
                     RPC (any running job with a PSServer — dist_async
                     training, the elastic membership server — doubles
                     as a scrape endpoint, no extra port)
  --self-test        emit a tiny in-process registry (smoke/demo)
  --trace OUT.json   export THIS process's merged causal-tracing +
                     profiler span stream as Chrome-trace JSON
                     (ISSUE 14; open in chrome://tracing or perfetto —
                     combine with --self-test for a demo trace)

``--format=prom`` prints Prometheus text exposition (the scrape
integration path); ``--format=json`` prints the snapshot/dump verbatim.
For flight-recorder files, ``--events`` appends the event ring as JSONL
after the metrics.

Examples:
  python tools/telemetry_dump.py --file /tmp/mxtpu_flight.4242.json
  python tools/telemetry_dump.py --host 127.0.0.1 --port 9090 --format=prom
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_file(path):
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    # flight dump wraps the snapshot under "metrics"; a bare snapshot
    # has "counters"/"gauges" at top level
    if "metrics" in payload and "counters" not in payload:
        return payload, payload["metrics"]
    return payload, payload


def _scrape(host, port, fmt):
    from mxnet_tpu.kvstore.ps_server import PSClient
    client = PSClient(host, port, retries=3)
    try:
        return client.telemetry(fmt=fmt)
    finally:
        client.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--file", help="flight-recorder dump or snapshot JSON")
    ap.add_argument("--host", help="PS server host for a live scrape")
    ap.add_argument("--port", type=int, help="PS server port")
    ap.add_argument("--format", choices=("prom", "json"), default="prom")
    ap.add_argument("--events", action="store_true",
                    help="also print the event ring (flight dumps) as "
                         "JSONL")
    ap.add_argument("--self-test", action="store_true",
                    help="render a tiny in-process registry and exit")
    ap.add_argument("--trace", metavar="OUT",
                    help="write the merged tracing + profiler span "
                         "stream as Chrome-trace JSON to OUT")
    args = ap.parse_args(argv)

    from mxnet_tpu.telemetry.prom import prom_text

    if args.self_test:
        from mxnet_tpu import telemetry
        from mxnet_tpu.telemetry import tracing
        telemetry.inc("selftest.counter", 3)
        telemetry.set_gauge("selftest.gauge", 1.5)
        telemetry.observe("selftest.ms", 2.0)
        with tracing.span("selftest.root", demo=True):
            with tracing.span("selftest.child"):
                pass
        snap = telemetry.snapshot()
        print(prom_text(snap) if args.format == "prom"
              else json.dumps(snap, indent=1))
        if not args.trace:
            return 0

    if args.trace:
        from mxnet_tpu.telemetry import tracing
        payload = tracing.chrome_trace()
        with open(args.trace, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        n = sum(1 for ev in payload["traceEvents"]
                if ev.get("ph") != "M")
        print(f"# wrote {n} trace event(s) to {args.trace}")
        return 0

    if args.file:
        payload, snap = _load_file(args.file)
        if args.format == "json":
            print(json.dumps(payload, indent=1))
        else:
            if payload is not snap and "reason" in payload:
                print(f"# flight dump: reason={payload['reason']!r} "
                      f"pid={payload.get('pid')} "
                      f"t={payload.get('time')}")
            print(prom_text(snap), end="")
        if args.events and payload is not snap:
            for ev in payload.get("events", []):
                print(json.dumps(ev))
        return 0

    if args.host and args.port:
        out = _scrape(args.host, args.port, args.format)
        if args.format == "prom":
            print(out.get("text", ""), end="")
        else:
            print(json.dumps(out, indent=1))
        return 0

    ap.error("need --file, --host/--port, or --self-test")
    return 2


if __name__ == "__main__":
    sys.exit(main())
