#!/usr/bin/env python
"""Pack images into RecordIO .rec files (reference: tools/im2rec.py).

Two modes, like the reference:
  --list: walk an image root, write a .lst file (index\tlabel\tpath)
  pack  : read a .lst, write .rec/.idx with IRHeader-framed JPEG bytes

The reference optionally re-encodes/resizes via OpenCV; this image has no
cv2, so bytes are packed as-is (``--pass-through``, the recommended mode
for TPU input pipelines anyway — decode happens in the native C++ pipeline,
src/image_decode.cc).
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXTS = (".jpg", ".jpeg", ".png")


def make_list(root, prefix, train_ratio=1.0, shuffle=True, seed=42):
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    items = []
    for label, cls in enumerate(classes):
        for dirpath, _, files in os.walk(os.path.join(root, cls)):
            for fn in files:
                if fn.lower().endswith(EXTS):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    items.append((rel, label))
    if shuffle:
        random.Random(seed).shuffle(items)
    n_train = int(len(items) * train_ratio)
    splits = [("train", items[:n_train])] if train_ratio < 1.0 else \
        [("", items)]
    if train_ratio < 1.0:
        splits.append(("val", items[n_train:]))
    for tag, chunk in splits:
        name = f"{prefix}_{tag}.lst" if tag else f"{prefix}.lst"
        with open(name, "w") as f:
            for i, (rel, label) in enumerate(chunk):
                f.write(f"{i}\t{label}\t{rel}\n")
        print(f"wrote {name} ({len(chunk)} items, {len(classes)} classes)")


def pack(lst_path, root, prefix):
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx, label, rel = int(parts[0]), float(parts[1]), parts[-1]
            with open(os.path.join(root, rel), "rb") as img:
                buf = img.read()
            header = recordio.IRHeader(0, label, idx, 0)
            rec.write_idx(idx, recordio.pack(header, buf))
            n += 1
    rec.close()
    print(f"wrote {prefix}.rec / {prefix}.idx ({n} records)")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (or .lst path when packing)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate .lst instead of packing")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--no-shuffle", action="store_true")
    args = ap.parse_args()
    if args.list:
        make_list(args.root, args.prefix, args.train_ratio,
                  shuffle=not args.no_shuffle)
    else:
        lst = args.prefix if args.prefix.endswith(".lst") \
            else args.prefix + ".lst"
        out = lst[:-4]
        pack(lst, args.root, out)


if __name__ == "__main__":
    main()
