"""Opportunistic serial runner for the queued on-chip experiments (round 4).

The axon tunnel to the single TPU chip heals and wedges unpredictably
(rounds 3-4 both lost measurement windows to it).  This runner turns the
verify-skill runbook queue into a state machine so measurements happen the
moment the tunnel answers, without a human in the loop:

  1. conv_matrix  — tools/tpu_conv_experiments.py, ONE config per child
                    process (s2d/NHWC/batch knobs; winner picked here)
  2. bench        — python bench.py with the winning knobs exported;
                    refreshes .bench_last_tpu.json (full payload incl.
                    tpu_bandwidth + flash evidence + scaling projection)
  3. flash_sweep  — tools/flash_long_seq.py (flash vs scan vs naive,
                    L in {2k,4k,8k}, peak-HBM per config)
  4. bert128      — MXTPU_BENCH_MODEL=bert MXTPU_BENCH_BERT_BATCH=128
                    (cache-safe: bench.py only caches model=all runs)

Rules encoded from .claude/skills/verify/SKILL.md:
  - ONE TPU client at a time; every step is a subprocess and the runner
    refuses to start while another known TPU client is alive.
  - Before each step the tunnel is probed with a real matmul in a
    throwaway subprocess; on failure the runner sleeps and retries
    rather than launching a doomed client.
  - Timeouts terminate children with SIGTERM then a grace period before
    SIGKILL (hard kills have wedged the relay for hours).

``--chaos`` runs the fault-tolerance smoke instead (CPU mesh, no TPU,
no queue lock): kill-the-writer + preempt-at-K + corrupt-newest +
auto-resume with bitwise parity (mxnet_tpu/testing/chaos.py).

State lives in .tpu_queue/state.json; completed steps are skipped on
restart, so the runner is safe to re-launch any time.  The conv-matrix
winner is written to <repo>/.bench_knobs.json, which is DELIBERATELY
git-tracked evidence: the driver's round-end `python bench.py` picks the
measured best config up from it (bench._apply_knobs_file).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
QDIR = os.path.join(REPO, ".tpu_queue")
STATE = os.path.join(QDIR, "state.json")

PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "v = jnp.ones((256, 256)) @ jnp.ones((256, 256));"
    "v.block_until_ready();"
    "print('PROBE_OK', d[0].platform)"
)

CONV_CONFIGS = ["base", "s2d", "nhwc", "s2d_nhwc",
                "b256", "b256_s2d", "b256_s2d_nhwc"]


def _log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)


def _load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"done": {}, "conv_results": []}


def _save_state(st: dict) -> None:
    os.makedirs(QDIR, exist_ok=True)
    with open(STATE + ".tmp", "w") as f:
        json.dump(st, f, indent=1)
    os.replace(STATE + ".tmp", STATE)


def _other_tpu_clients() -> list[str]:
    """Best-effort scan for known TPU-client processes we didn't start."""
    try:
        out = subprocess.run(["ps", "-eo", "pid,args"], capture_output=True,
                             text=True, timeout=10).stdout
    except Exception:  # noqa: BLE001
        return []
    me = os.getpid()
    hits = []
    for line in out.splitlines():
        parts = line.strip().split(None, 2)
        if len(parts) < 3:
            continue
        pid, exe, rest = parts[0], parts[1], parts[2]
        # only python processes RUNNING one of the client scripts — the
        # driver's own command line merely MENTIONS these names in its
        # prompt text and must not count as a client
        if "python" not in os.path.basename(exe):
            continue
        args_head = rest.split("--", 1)[0]
        if any(k in args_head for k in ("tpu_conv_experiments",
                                        "flash_long_seq", "bench.py",
                                        "memory_levers")):
            if pid.isdigit() and int(pid) != me:
                hits.append(line.strip())
    return hits


def _run_child(cmd: list[str], env: dict, timeout: float,
               log_path: str) -> tuple[int | None, str]:
    """Run a TPU-client subprocess with graceful timeout termination.

    Returns (returncode or None on timeout, captured stdout)."""
    with open(log_path, "a") as logf:
        logf.write(f"\n=== {time.strftime('%F %T')} {' '.join(cmd)}\n")
        logf.flush()
        # own session so a timeout can terminate the whole process GROUP —
        # some client tools spawn their own subprocess children
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=logf, text=True, cwd=REPO,
                             start_new_session=True)
        deadline = time.time() + timeout
        chunks: list[str] = []
        # raw chunk reads — readline() could block past the deadline on a
        # wedged child that flushed a partial line (the exact failure mode
        # this runner exists to escape)
        import selectors
        fd = p.stdout.fileno()
        os.set_blocking(fd, False)
        sel = selectors.DefaultSelector()
        sel.register(p.stdout, selectors.EVENT_READ)
        while True:
            if p.poll() is not None:
                while True:   # drain what the pipe still holds
                    try:
                        data = os.read(fd, 65536)
                    except (BlockingIOError, OSError):
                        break
                    if not data:
                        break
                    text = data.decode("utf-8", "replace")
                    chunks.append(text)
                    logf.write(text)
                return p.returncode, "".join(chunks)
            if time.time() > deadline:
                break
            eof = False
            for _ in sel.select(timeout=5.0):
                try:
                    raw = os.read(fd, 65536)
                except BlockingIOError:
                    continue
                if not raw:
                    # EOF while the child lives: the fd stays readable
                    # forever, so select() would return instantly every
                    # loop — a tight CPU spin for up to the full step
                    # timeout. Drop to plain poll+sleep instead.
                    eof = True
                    break
                data = raw.decode("utf-8", "replace")
                chunks.append(data)
                logf.write(data)
                logf.flush()
            if eof:
                sel.unregister(p.stdout)
                while p.poll() is None and time.time() <= deadline:
                    time.sleep(5.0)
        # timed out: SIGTERM the group, grace, then SIGKILL as last resort
        _log(f"timeout after {timeout:.0f}s: TERM -> group {p.pid}")
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            _log(f"no exit after TERM; KILL -> group {p.pid}")
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        return None, "".join(chunks)


def _probe(timeout: float = 150.0) -> bool:
    try:
        r = subprocess.run([sys.executable, "-c", PROBE_SRC],
                           capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout and \
        "cpu" not in r.stdout.split("PROBE_OK", 1)[1]


_DEADLINE = float(os.environ.get("MXTPU_QUEUE_DEADLINE", "0") or 0)


class _DeadlineReached(Exception):
    pass


def _check_deadline() -> None:
    """The driver runs its own bench at round end — this runner must not
    be holding the chip then. Past the deadline, stop cleanly between
    steps/configs (never mid-child)."""
    if _DEADLINE and time.time() > _DEADLINE:
        raise _DeadlineReached


def _wait_for_tunnel(st: dict) -> None:
    back = 120.0
    while True:
        _check_deadline()
        others = _other_tpu_clients()
        if others:
            _log(f"waiting: another TPU client is alive: {others[0][:100]}")
            time.sleep(60)
            continue
        if _probe():
            _log("tunnel probe OK")
            return
        st.setdefault("probe_failures", 0)
        st["probe_failures"] += 1
        _save_state(st)
        _log(f"tunnel probe failed (#{st['probe_failures']}); "
             f"sleeping {back:.0f}s")
        time.sleep(back)
        back = min(back * 1.5, 900.0)


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def step_conv_matrix(st: dict) -> None:
    done_cfgs = {r["config"] for r in st["conv_results"] if "error" not in r}
    for cfg in CONV_CONFIGS:
        if cfg in done_cfgs:
            continue
        _wait_for_tunnel(st)
        # MXTPU_EXP_CHILD runs ONE config in-process (no grandchildren
        # to orphan when a stuck client must be terminated)
        env = dict(os.environ, MXTPU_EXP_CHILD=cfg)
        rc, out = _run_child(
            [sys.executable, "tools/tpu_conv_experiments.py"], env,
            timeout=1500.0, log_path=os.path.join(QDIR, "conv.log"))
        lines = [l for l in _json_lines(out) if l.get("config") == cfg]
        if lines and "img_per_sec" in lines[-1] \
                and lines[-1].get("platform") == "tpu":
            st["conv_results"] = [r for r in st["conv_results"]
                                  if r.get("config") != cfg] + [lines[-1]]
            _log(f"conv config {cfg}: {lines[-1]['img_per_sec']} img/s")
        else:
            # a CPU-fallback number must NOT be recorded as a measurement
            err = (f"platform={lines[-1].get('platform')}" if lines
                   else f"rc={rc}")
            st["conv_results"] = [r for r in st["conv_results"]
                                  if r.get("config") != cfg] + \
                [{"config": cfg, "error": err, "out": out[-200:]}]
            _log(f"conv config {cfg} FAILED ({err})")
        _save_state(st)
    ok = [r for r in st["conv_results"] if "img_per_sec" in r]
    if len(ok) == len(CONV_CONFIGS):
        # only a full matrix marks the step done; a restart retries the
        # configs that failed or ran on the wrong platform
        st["done"]["conv_matrix"] = True
    if ok:
        best = max(ok, key=lambda r: r["img_per_sec"])
        st["best_conv"] = best
        _log(f"conv matrix best: {json.dumps(best)}")
        # bake the measured winner into bench.py's defaults so the
        # driver's plain `python bench.py` runs the best config
        knobs_path = os.path.join(REPO, ".bench_knobs.json")
        try:   # read-merge-write: flash_autotune keys must survive
            with open(knobs_path) as f:
                knobs = json.load(f)
        except (OSError, ValueError):
            knobs = {}
        knobs.update({
            "resnet_s2d": 1 if best.get("s2d_stem") else 0,
            # NCHW is the no-knob default; only a non-default layout
            # becomes an env export in bench._apply_knobs_file
            "conv_layout": (best["conv_layout"]
                            if best.get("conv_layout") not in
                            (None, "NCHW") else None),
            "batch": best.get("batch"),
            "measured_img_per_sec": best.get("img_per_sec"),
            "measured_at": time.strftime("%F %T")})
        with open(knobs_path, "w") as f:
            json.dump(knobs, f, indent=1)
    _save_state(st)


def step_bench(st: dict) -> None:
    _wait_for_tunnel(st)
    # winner knobs flow through .bench_knobs.json alone (bench.py's
    # _apply_knobs_file) — no env duplication to drift from it
    env = dict(os.environ)
    env["MXTPU_BENCH_PROBE_ATTEMPTS"] = "2"   # runner already probed
    # state.json wants the FULL payload, and this parser takes the last
    # json line — suppress the driver-facing compact headline
    env["MXTPU_BENCH_NO_COMPACT"] = "1"
    rc, out = _run_child([sys.executable, "bench.py"], env, timeout=2700.0,
                         log_path=os.path.join(QDIR, "bench.log"))
    lines = _json_lines(out)
    if lines:
        st["bench_last_line"] = lines[-1]
        plat = lines[-1].get("platform")
        _log(f"bench platform={plat} "
             f"value={lines[-1].get('value')}")
        if plat == "tpu":
            st["done"]["bench"] = True
            _bench_regression_gate(st)
    _save_state(st)


def _bench_regression_gate(st: dict) -> None:
    """ISSUE 11 satellite: the perf GATE.  Diff this run's full payload
    (.bench_full.json) against the newest prior-round trajectory file
    (BENCH_r*.json) with tools/bench_diff.py --fail-on-regression
    (threshold MXTPU_BENCH_REGRESSION_PCT, default 10).  bench_diff
    skips null-when-unmeasured fields, checks telemetry_schema_version,
    and refuses to gate cross-platform pairs — a CPU-fallback round
    cannot fake a TPU regression.  A non-zero exit is recorded in
    state.json and propagates out of main() when the queue drains."""
    import glob
    import subprocess
    rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    current = os.path.join(REPO, ".bench_full.json")
    if not rounds or not os.path.exists(current):
        return
    pct = os.environ.get("MXTPU_BENCH_REGRESSION_PCT", "10")
    cmd = [sys.executable, os.path.join(REPO, "tools", "bench_diff.py"),
           rounds[-1], current, "--fail-on-regression", pct, "--quiet"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    last = (r.stdout.strip().splitlines() or [""])[-1]
    _log(f"bench_diff vs {os.path.basename(rounds[-1])}: rc="
         f"{r.returncode} {last[:400]}")
    verdict = None
    if last.startswith("BENCHDIFF "):
        try:
            verdict = json.loads(last[len("BENCHDIFF "):])
        except ValueError:
            pass
    st["bench_regression"] = {"rc": r.returncode, "verdict": verdict}


FLASH_LS = (2048, 4096, 8192, 16384, 32768)


def step_flash_sweep(st: dict) -> None:
    """One (impl, L) config per direct child process, probe-gated.

    16k/32k rows are the footprint evidence: naive's (L,L) bf16 scores
    hit 8*32768^2*2 = 17 GB > the v5e's 16 GB HBM while flash stays
    O(L*D)."""
    from tools.flash_long_seq import child_env, parse_child_line, summarize
    results = st.setdefault("flash_results", [])
    done = {(r["impl"], r["L"]) for r in results
            if (r.get("ok") or r.get("oom")) and r.get("platform") == "tpu"}
    for L in FLASH_LS:
        for impl in ("flash", "scan", "naive"):
            if (impl, L) in done:
                continue
            _wait_for_tunnel(st)
            rc, out = _run_child(
                [sys.executable, "tools/flash_long_seq.py"],
                child_env(impl, L), timeout=900.0,
                log_path=os.path.join(QDIR, "flash.log"))
            r = parse_child_line(out)
            if r is None:
                r = {"impl": impl, "L": L, "ok": False,
                     "error": f"rc={rc} (timeout or crash)"}
            elif r.get("platform") != "tpu":
                r = {"impl": impl, "L": L, "ok": False,
                     "error": f"platform={r.get('platform')} (not tpu)"}
            results[:] = [x for x in results
                          if (x["impl"], x["L"]) != (impl, L)] + [r]
            _log(f"flash {impl}@L={L}: "
                 f"{r.get('ms', r.get('error', 'oom'))}")
            _save_state(st)
    st["flash_summary"] = summarize(results)
    measured = {(r["impl"], r["L"]) for r in results
                if (r.get("ok") or r.get("oom"))
                and r.get("platform") == "tpu"}
    if len(measured) == len(FLASH_LS) * 3:
        st["done"]["flash_sweep"] = True
    _save_state(st)


def step_memory_levers(st: dict) -> None:
    """One memory-lever config per child process (tools/memory_levers.py
    MATRIX): in-graph grad accumulation, blocked fused CE vs naive
    (incl. the size where naive must OOM), ZeRO-1 footprint report.
    Winner summary -> .bench_memlevers.json, which bench.py attaches."""
    from tools.memory_levers import MATRIX, summarize
    results = st.setdefault("memlever_results", [])
    done = {r["config"] for r in results
            if r.get("platform") == "tpu" and "error" not in r}
    for cfg in MATRIX:
        if cfg in done:
            continue
        _wait_for_tunnel(st)
        env = dict(os.environ, MXTPU_EXP_CHILD=cfg)
        rc, out = _run_child(
            [sys.executable, "tools/memory_levers.py"], env,
            timeout=1500.0, log_path=os.path.join(QDIR, "memlevers.log"))
        lines = [l for l in _json_lines(out) if l.get("config") == cfg]
        if lines and lines[-1].get("platform") == "tpu":
            r = lines[-1]
            _log(f"memlever {cfg}: "
                 f"{r.get('ms_per_step', r.get('oom', '?'))}")
        else:
            r = {"config": cfg,
                 "error": (f"platform={lines[-1].get('platform')}"
                           if lines else f"rc={rc}"),
                 "out": out[-200:]}
            _log(f"memlever {cfg} FAILED ({r['error']})")
        results[:] = [x for x in results if x.get("config") != cfg] + [r]
        _save_state(st)
    ok = {r["config"] for r in results
          if r.get("platform") == "tpu" and "error" not in r}
    if len(ok) == len(MATRIX):
        st["done"]["memory_levers"] = True
    if ok:
        summary = summarize([r for r in results if "error" not in r])
        summary["measured_at"] = time.strftime("%F %T")
        st["memlever_summary"] = summary
        with open(os.path.join(REPO, ".bench_memlevers.json"), "w") as f:
            json.dump(summary, f, indent=1)
    _save_state(st)


FLASH_TUNE = [(256, 256), (256, 512), (512, 256), (512, 512),
              (512, 1024), (1024, 512), (1024, 1024)]


def step_flash_autotune(st: dict) -> None:
    """Sweep Pallas flash-attention block sizes (MXTPU_FLASH_BQ/BK) at
    L=4096 and bake the fastest pair into .bench_knobs.json (the manual
    follow-up the verify runbook used to list)."""
    from tools.flash_long_seq import child_env, parse_child_line
    results = st.setdefault("flash_tune_results", [])
    done = {(r["bq"], r["bk"]) for r in results if r.get("ok")}
    for bq, bk in FLASH_TUNE:
        if (bq, bk) in done:
            continue
        _wait_for_tunnel(st)
        env = child_env("flash", 4096)
        env["MXTPU_FLASH_BQ"] = str(bq)
        env["MXTPU_FLASH_BK"] = str(bk)
        rc, out = _run_child(
            [sys.executable, "tools/flash_long_seq.py"], env,
            timeout=900.0, log_path=os.path.join(QDIR, "flashtune.log"))
        r = parse_child_line(out)
        if r and r.get("ok") and r.get("platform") == "tpu":
            rec = {"bq": bq, "bk": bk, "ms": r["ms"], "ok": True}
            _log(f"flash tune bq={bq} bk={bk}: {r['ms']} ms")
        else:
            rec = {"bq": bq, "bk": bk, "ok": False,
                   "error": (f"platform={r.get('platform')}" if r
                             else f"rc={rc}")}
            _log(f"flash tune bq={bq} bk={bk} FAILED ({rec['error']})")
        results[:] = [x for x in results
                      if (x["bq"], x["bk"]) != (bq, bk)] + [rec]
        _save_state(st)
    ok = [r for r in results if r.get("ok")]
    if len(ok) == len(FLASH_TUNE):
        st["done"]["flash_autotune"] = True
    if ok:
        best = min(ok, key=lambda r: r["ms"])
        st["flash_tune_best"] = best
        knobs_path = os.path.join(REPO, ".bench_knobs.json")
        try:
            with open(knobs_path) as f:
                knobs = json.load(f)
        except (OSError, ValueError):
            knobs = {}
        knobs["flash_bq"], knobs["flash_bk"] = best["bq"], best["bk"]
        knobs["flash_tuned_at"] = time.strftime("%F %T")
        with open(knobs_path, "w") as f:
            json.dump(knobs, f, indent=1)
    _save_state(st)


def step_bert128(st: dict) -> None:
    _wait_for_tunnel(st)
    env = dict(os.environ, MXTPU_BENCH_MODEL="bert",
               MXTPU_BENCH_BERT_BATCH="128",
               MXTPU_BENCH_PROBE_ATTEMPTS="2",
               MXTPU_BENCH_NO_COMPACT="1")   # keep the full last line
    rc, out = _run_child([sys.executable, "bench.py"], env, timeout=2700.0,
                         log_path=os.path.join(QDIR, "bert128.log"))
    lines = _json_lines(out)
    if lines:
        st["bert128"] = lines[-1]
        if lines[-1].get("platform") == "tpu":
            st["done"]["bert128"] = True
            _log(f"bert128: {lines[-1].get('value')} samples/s")
    _save_state(st)


def run_chaos(suite: str = "preempt") -> int:
    """``--chaos [elastic|serving|autoscale|watchdog|fleet|procs|all]``:
    the fault-tolerance smoke (mxnet_tpu.testing.chaos) in a child
    process on the simulated
    CPU mesh.  Default suite: kill the checkpoint writer, preempt at
    step K, corrupt the newest checkpoint, auto-resume, bitwise parity.
    ``elastic`` (ISSUE 8): kill worker 1 at step K via silent
    heartbeats, join a replacement at K', kill a reshard mid-transfer —
    each continuing WITHOUT a restart and bitwise-matching a fresh
    process restored from the same state.  ``serving`` (ISSUE 12): kill
    a serving-router replica mid-traffic — the router must requeue with
    zero lost/duplicated requests and every output must match the solo
    cold-path stream exactly; runs under ``MXTPU_KV_DTYPE=fp8``
    (ISSUE 20), so the bitwise gate holds within the quantized mode
    and a teacher-forced fp32 drift bound rides along.  ``autoscale`` (ISSUE 13): a preemption
    NOTICE drains worker 1 at a boundary ahead of the heartbeat
    timeout (checkpoint-then-reshard 8->4, serving admissions shed),
    the notice is revoked and the load-based autoscaler grows back
    4->8 — bitwise vs a fresh restore at EACH dp, a noticed serving
    replica drained with zero lost requests, a replacement replica
    autoscaled in with zero new compiles, flight-dump + racecheck +
    KV-leak gates folded into the verdict.  ``watchdog`` (ISSUE 14): a
    NaN loss injected through the ``watchdog.loss`` fault point and a
    FakeClock step stall must each leave a typed ``watchdog.*`` event
    and a flight dump whose reason names the rule
    (``watchdog:nonfinite_loss`` / ``watchdog:step_stall``).  ``fleet``
    (ISSUE 15): N simulated workers under FakeClock with one injected
    straggler and one scrape-dead rank — the FleetCollector must name
    both BY RANK in typed ``fleet.*`` events with matching flight
    dumps, merged histograms must equal per-rank bucket sums bitwise,
    racecheck zero on the collector locks.  ``procs`` (ISSUE 19): the
    one suite with REAL processes — a 4-process ``jax.distributed`` pod
    (mxnet_tpu.pod.PodLauncher), one worker SIGKILLed at a step gate;
    survivors must re-init the coordination service at
    ``jax.process_count()==3`` and resume BITWISE a fresh 3-process pod
    restored from the same checkpoint, with the serving ledger
    exactly-once and a real fleet scrape naming the dead rank typed.
    Needs no
    TPU and takes no queue lock: safe to run any time, including while
    the measurement queue owns the chip."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # ISSUE 10: every chaos interleaving runs under the runtime race /
    # lock-order detector (mxnet_tpu.lint.racecheck); a finding fails
    # the scenario exactly like a parity miss
    env.setdefault("MXTPU_RACECHECK", "1")
    # ISSUE 16: and under the use-after-donate sentinel
    # (mxnet_tpu.lint.donation) — a stale host touch of a donated
    # buffer fails the scenario the way the first TPU round would crash
    env.setdefault("MXTPU_DONATION_CHECK", "1")
    # ISSUE 17: serving scenarios run SPECULATIVE — the replica kill
    # lands mid-draft and the outputs_match_solo gate proves the
    # drain/requeue loses zero requests and re-verifies onto the exact
    # plain-path stream.  spec_k=2 bounds the verify-graph warmup
    # compiles on the CPU mesh (widths {2, 4} only).
    if suite in ("serving", "autoscale", "all"):
        env.setdefault("MXTPU_SPEC_DECODE", "1")
        env.setdefault("MXTPU_SPEC_K", "2")
    # ISSUE 20: the serving scenario stores every KV pool in fp8 — the
    # bitwise fleet-vs-solo gate then runs WITHIN the quantized mode
    # (replica kill + requeue must land on the fp8 solo stream), and
    # the scenario adds the teacher-forced fp32 drift bound
    # (kv_drift_ok) on top.
    if suite in ("serving", "all"):
        env.setdefault("MXTPU_KV_DTYPE", "fp8")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    _log(f"chaos smoke [{suite}]: starting (CPU mesh, ~1 min)")
    r = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.testing.chaos", suite],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    verdicts = _json_lines(r.stdout)
    if r.returncode == 0 and verdicts and verdicts[-1].get("ok"):
        # ISSUE 9: every scenario that injected a kill must have left a
        # parseable flight-recorder dump (chaos records the check per
        # scenario; None = telemetry kill switch, nothing to assert)
        bad = [s.get("kind") or s.get("mode")
               for s in verdicts[-1].get("chaos", [])
               if "flight_dump" in s and s["flight_dump"] is not None
               and not s["flight_dump"].get("ok")]
        if bad:
            _log(f"chaos smoke: FAILED — injected kill left no valid "
                 f"flight-recorder dump in scenario(s) {bad}")
            return 1
        # ISSUE 10: zero racecheck findings after every scenario
        raced = [s.get("kind") or s.get("mode")
                 for s in verdicts[-1].get("chaos", [])
                 if s.get("racecheck") is not None
                 and not s["racecheck"].get("ok")]
        if raced:
            _log(f"chaos smoke: FAILED — racecheck findings in "
                 f"scenario(s) {raced}")
            return 1
        # ISSUE 16: zero use-after-donate findings after every scenario
        donated = [s.get("kind") or s.get("mode")
                   for s in verdicts[-1].get("chaos", [])
                   if s.get("donation") is not None
                   and not s["donation"].get("ok")]
        if donated:
            _log(f"chaos smoke: FAILED — use-after-donate findings in "
                 f"scenario(s) {donated}")
            return 1
        _log("chaos smoke: OK " + json.dumps(verdicts[-1]))
        return 0
    _log(f"chaos smoke: FAILED rc={r.returncode}\n"
         f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
    return 1


STEPS = [("conv_matrix", step_conv_matrix), ("bench", step_bench),
         ("memory_levers", step_memory_levers),
         ("flash_sweep", step_flash_sweep),
         ("flash_autotune", step_flash_autotune),
         ("bert128", step_bert128)]


_LOCK_FD = None   # held for process lifetime; flock dies with the process


def _acquire_lock() -> bool:
    """One runner per machine: a second instance (whose probes and clients
    the process scan cannot see) must refuse to start.  flock, not a
    pidfile — the kernel releases it on ANY exit, and a recycled pid
    cannot fake liveness."""
    global _LOCK_FD
    import fcntl
    lock = os.path.join(QDIR, "runner.lock")
    _LOCK_FD = open(lock, "w")
    try:
        fcntl.flock(_LOCK_FD, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        _log(f"another runner holds {lock}; exiting")
        return False
    _LOCK_FD.write(str(os.getpid()))
    _LOCK_FD.flush()
    return True


def main() -> int:
    args = sys.argv[1:]
    if "--chaos" in args:
        after = args[args.index("--chaos") + 1:]
        suite = after[0] if after and not after[0].startswith("--") \
            else "preempt"
        return run_chaos(suite)
    os.makedirs(QDIR, exist_ok=True)
    if not _acquire_lock():
        return 1
    only = os.environ.get("MXTPU_QUEUE_STEPS")
    # perpetual: transient per-config failures (half-healed tunnel,
    # flaky compiles) retry on the next pass instead of needing a human
    # relaunch; exits only when every wanted step is done
    while True:
        st = _load_state()
        wanted = only.split(",") if only else [n for n, _ in STEPS]
        try:
            for name, fn in STEPS:
                if name not in wanted:
                    continue
                if st["done"].get(name):
                    _log(f"step {name}: already done, skipping")
                    continue
                _log(f"step {name}: starting")
                fn(st)
        except _DeadlineReached:
            _log("deadline reached: standing down so the driver's own "
                 "bench owns the chip")
            return 0
        pending = [n for n in wanted if not st["done"].get(n)]
        if not pending:
            _log("queue complete: " + json.dumps(st.get("done", {})))
            if st.get("bench_regression", {}).get("rc"):
                # the bench_diff gate tripped: everything ran, but the
                # queue's exit code says this round got SLOWER
                _log("bench regression gate tripped (exit 3): "
                     + json.dumps(st["bench_regression"].get("verdict")))
                return 3
            return 0
        _log(f"pass finished with pending steps {pending}; "
             f"sleeping 600s before the next pass")
        time.sleep(600)


if __name__ == "__main__":
    sys.exit(main())
