"""On-chip measurement of the three memory levers (VERDICT r4 weak #4).

Each config runs in ITS OWN child process (MXTPU_EXP_CHILD), so
``device.memory_stats()['peak_bytes_in_use']`` isolates that config's
peak HBM.  One JSON line per config on stdout; the queue runner
(tools/tpu_queue_runner.py step_memory_levers) collects them into
``.bench_memlevers.json``, which bench.py attaches to its payload.

Levers (all correctness-proven on the virtual mesh in tests/):
  accum_*   — in-graph gradient accumulation (lax.scan microbatching,
              DataParallelTrainer.step_accum) vs the one-shot big batch:
              peak HBM should fall with n_micro, wall-clock/sample cost
              is the price.  Reference analog: example/image-class
              gradient accumulation for >GPU-memory batches.
  ce_*      — blocked fused linear+CE (ops/blocked_cross_entropy.py,
              never materializes the (N, V) logits) vs the naive
              materialized path at V in {32k, 128k} + an N*V size where
              naive OOMs a 16 GB chip and fused must survive.
  zero1     — single-chip report: measured param/adam-state HBM plus the
              analytic 1/N split ZeRO-1 gives at 8/256 chips.  The
              on/off STEP-TIME delta needs dp>1 and real wire — not
              measurable on one chip (shard_updates is a no-op at dp=1);
              correctness is covered by the multichip dryrun oracle.
"""
from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

MATRIX = {
    "accum_base": dict(kind="accum", n_micro=1),
    "accum_4": dict(kind="accum", n_micro=4),
    "accum_8": dict(kind="accum", n_micro=8),
    "ce_naive_32k": dict(kind="ce", impl="naive", vocab=32768,
                         tokens=8192),
    "ce_fused_32k": dict(kind="ce", impl="fused", vocab=32768,
                         tokens=8192),
    "ce_naive_128k": dict(kind="ce", impl="naive", vocab=131072,
                          tokens=8192),
    "ce_fused_128k": dict(kind="ce", impl="fused", vocab=131072,
                          tokens=8192),
    # 32768 tokens x 131072 vocab: logits alone = 16 GB fp32 — past the
    # v5e's HBM. naive must OOM (that IS the datum); fused must survive.
    "ce_naive_oom32k": dict(kind="ce", impl="naive", vocab=131072,
                            tokens=32768, expect_oom=True),
    "ce_fused_32ktok": dict(kind="ce", impl="fused", vocab=131072,
                            tokens=32768),
    "zero1": dict(kind="zero1"),
}


def _peak_mb():
    import jax
    stats = jax.devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    return round(peak / 1e6, 1) if peak is not None else None


def _platform():
    import jax
    return jax.devices()[0].platform


def _run_accum(n_micro):
    """ResNet-18, global batch 256 via one shot (n_micro=1) or scan
    microbatching: samples/s + peak HBM."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer
    import jax

    batch = int(os.environ.get("MXTPU_LEVER_BATCH", "256"))
    size = int(os.environ.get("MXTPU_LEVER_IMG", "128"))
    iters = int(os.environ.get("MXTPU_LEVER_ITERS", "10"))
    if _platform() == "cpu":   # smoke scale
        batch, size, iters = 32, 64, 2

    net = resnet18_v1()
    net.initialize()
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "adam", {"learning_rate": 1e-3}, mesh=mesh)
    data = mx.nd.array(np.random.RandomState(0).rand(
        batch, 3, size, size).astype(np.float32))
    label = mx.nd.zeros((batch,))

    def one_step():
        if n_micro == 1:
            return tr.step(data, label)
        return tr.step_accum(data, label, n_micro=n_micro)

    loss = one_step()           # compile + warmup
    loss.asnumpy()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = one_step()
    loss.asnumpy()
    dt = (time.perf_counter() - t0) / iters
    return {"samples_per_sec": round(batch / dt, 1),
            "ms_per_step": round(dt * 1e3, 2),
            "batch": batch, "img": size, "peak_hbm_mb": _peak_mb()}


def _run_ce(impl, vocab, tokens, expect_oom=False):
    """Fused blocked CE vs naive materialized logits: fwd+bwd of the
    mean loss over a (tokens, d) x (d, vocab) head."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.blocked_cross_entropy import \
        fused_linear_cross_entropy

    d = int(os.environ.get("MXTPU_LEVER_D", "1024"))
    iters = int(os.environ.get("MXTPU_LEVER_ITERS", "10"))
    if _platform() == "cpu":   # smoke scale
        tokens, vocab, d, iters = min(tokens, 512), min(vocab, 2048), \
            256, 2
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (tokens, d), jnp.bfloat16)
    w = jax.random.normal(key, (d, vocab), jnp.bfloat16) * 0.02
    t = jax.random.randint(key, (tokens,), 0, vocab)

    if impl == "fused":
        def loss_fn(x, w):
            return fused_linear_cross_entropy(x, w, t).mean()
    else:
        def loss_fn(x, w):
            logits = (x.astype(jnp.float32) @ w.astype(jnp.float32))
            lse = jax.nn.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, t[:, None], 1)[:, 0]
            return (lse - picked).mean()

    step = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))
    try:
        (v, g) = step(x, w)
        jax.block_until_ready((v, g))
    except Exception as e:  # noqa: BLE001 — OOM is a datum here
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            return {"oom": True, "vocab": vocab, "tokens": tokens,
                    "expected_oom": expect_oom,
                    "error": msg.splitlines()[0][:200]}
        raise
    t0 = time.perf_counter()
    for _ in range(iters):
        (v, g) = step(x, w)
    jax.block_until_ready((v, g))
    dt = (time.perf_counter() - t0) / iters
    return {"oom": False, "vocab": vocab, "tokens": tokens, "d": d,
            "ms_per_step": round(dt * 1e3, 2),
            "peak_hbm_mb": _peak_mb(), "loss": round(float(v), 4),
            "expected_oom": expect_oom}


def _run_zero1():
    """Measured single-chip param + adam-state footprint, plus the
    analytic per-chip optimizer memory ZeRO-1 yields over dp (the
    step-time delta needs >1 chip — see module docstring)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer
    import jax

    net = resnet50_v1()
    net.initialize()
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "adam", {"learning_rate": 1e-3}, mesh=mesh)
    b = 8
    data = mx.nd.array(np.random.RandomState(0).rand(
        b, 3, 64, 64).astype(np.float32))
    loss = tr.step(data, mx.nd.zeros((b,)))
    loss.asnumpy()
    param_b = sum(int(np.prod(p.shape)) * 4 for p in tr._param_objs)
    # adam: m + v per param, fp32
    opt_b = 2 * param_b
    out = {"param_mb": round(param_b / 1e6, 1),
           "adam_state_mb": round(opt_b / 1e6, 1),
           "peak_hbm_mb_step": _peak_mb(),
           "note": "step-time on/off needs dp>1 (no-op on one chip); "
                   "RS+AG == ring AR wire bytes, savings are state/N"}
    for n in (8, 256):
        out[f"adam_state_mb_per_chip_zero1_dp{n}"] = round(
            opt_b / n / 1e6, 2)
    return out


def run_config(name, kind, **kw):
    t0 = time.perf_counter()
    if kind == "accum":
        r = _run_accum(kw["n_micro"])
        r["n_micro"] = kw["n_micro"]
    elif kind == "ce":
        r = _run_ce(kw["impl"], kw["vocab"], kw["tokens"],
                    kw.get("expect_oom", False))
        r["impl"] = kw["impl"]
    else:
        r = _run_zero1()
    r.update(config=name, kind=kind, platform=_platform(),
             wall_s=round(time.perf_counter() - t0, 1))
    print(json.dumps(r), flush=True)
    return r


def summarize(results):
    """Flat scalar summary for bench.py's payload (and headline sweep)."""
    by = {r["config"]: r for r in results if isinstance(r, dict)}
    out = {}

    def put(dst, cfg, src):
        r = by.get(cfg)
        if r and src in r and r[src] is not None:
            out[dst] = r[src]

    for cfg, tag in (("accum_base", "accum1"), ("accum_4", "accum4"),
                     ("accum_8", "accum8")):
        put(f"{tag}_ms", cfg, "ms_per_step")
        put(f"{tag}_hbm_mb", cfg, "peak_hbm_mb")
    for v in ("32k", "128k"):
        for impl in ("naive", "fused"):
            put(f"ce_{impl}_{v}_ms", f"ce_{impl}_{v}", "ms_per_step")
            put(f"ce_{impl}_{v}_hbm_mb", f"ce_{impl}_{v}", "peak_hbm_mb")
    r = by.get("ce_naive_oom32k")
    if r is not None:
        out["ce_naive_32ktok_oom"] = bool(r.get("oom"))
    put("ce_fused_32ktok_ms", "ce_fused_32ktok", "ms_per_step")
    put("ce_fused_32ktok_hbm_mb", "ce_fused_32ktok", "peak_hbm_mb")
    put("param_mb", "zero1", "param_mb")
    put("adam_state_mb", "zero1", "adam_state_mb")
    put("zero1_dp8_state_mb", "zero1", "adam_state_mb_per_chip_zero1_dp8")
    put("zero1_dp256_state_mb", "zero1",
        "adam_state_mb_per_chip_zero1_dp256")
    return out


def main():
    child = os.environ.get("MXTPU_EXP_CHILD")
    if child:   # child: exactly ONE config, never recurse
        cfg = dict(MATRIX[child])
        run_config(child, cfg.pop("kind"), **cfg)
        return
    want = os.environ.get("MXTPU_EXP_CONFIGS")
    names = want.split(",") if want else list(MATRIX)
    for n in names:
        env = dict(os.environ, MXTPU_EXP_CHILD=n)
        line, err = _run_child_graceful(
            [sys.executable, os.path.abspath(__file__)], env, 1500.0)
        print(line if line
              else json.dumps({"config": n, "error": err}), flush=True)


def _run_child_graceful(cmd, env, timeout):
    """TPU-client child with SIGTERM-then-grace termination (NEVER a
    bare SIGKILL first — hard kills have wedged the tunnel relay for
    hours; same protocol as tools/tpu_queue_runner._run_child)."""
    import signal
    import subprocess
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True,
                         start_new_session=True)
    try:
        out, _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            out, _ = p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            out, _ = p.communicate()
        lines = [l for l in (out or "").splitlines()
                 if l.startswith("{")]
        return (lines[-1] if lines else None), f"timeout after {timeout}s"
    lines = [l for l in (out or "").splitlines() if l.startswith("{")]
    return (lines[-1] if lines else None), "no output"


if __name__ == "__main__":
    main()
