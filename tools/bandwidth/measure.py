#!/usr/bin/env python
"""KVStore push/pull bandwidth microbenchmark
(reference: tools/bandwidth/measure.py — the third BASELINE metric).

Times `kv.pushpull` over ResNet-sized gradient buffers and reports GB/s
against the device's theoretical bound. On a mesh the pushpull is the
in-graph psum over the data axis (ICI); single-chip it measures the
dispatch+copy floor.

    python tools/bandwidth/measure.py --kv-store device --data-mb 100
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--num-keys", type=int, default=20,
                    help="number of gradient tensors (ResNet-50 has ~160)")
    ap.add_argument("--data-mb", type=float, default=100.0,
                    help="total payload size in MB")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--gc-type", default=None, choices=["2bit", "int8"],
                    help="gradient compression on the wire hop (reference "
                         "dist_sync_kvstore.py --gc-type; int8 is the "
                         "EQuARX-style extension)")
    args = ap.parse_args()

    import mxnet_tpu as mx

    kv = mx.kv.create(args.kv_store)
    if args.gc_type:
        if not kv.type.startswith("dist"):
            ap.error(f"--gc-type applies to the cross-worker wire hop; "
                     f"kvstore {kv.type!r} has none (use dist_sync)")
        kv.set_gradient_compression({"type": args.gc_type})
    total_elems = int(args.data_mb * 1e6 / 4)
    per_key = total_elems // args.num_keys
    vals = []
    for k in range(args.num_keys):
        v = mx.nd.random.uniform(shape=(per_key,))
        kv.init(k, v)
        vals.append(v)

    keys = list(range(args.num_keys))

    def run_batched():
        # one pushpull call: the dist store coalesces into
        # MXTPU_KVSTORE_BIGARRAY_BOUND buckets — one wire round per bucket
        kv.pushpull(keys, vals, out=vals)
        vals[-1].wait_to_read()

    def run_per_key():
        for k, v in enumerate(vals):
            kv.pushpull(k, v, out=v)
        vals[-1].wait_to_read()

    results = {}
    for name, run_once in (("batched", run_batched),
                           ("per-key", run_per_key)):
        for _ in range(args.warmup):
            run_once()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            run_once()
        dt = time.perf_counter() - t0
        total_bytes = args.iters * total_elems * 4 * 2     # push + pull
        results[name] = total_bytes / dt / 1e9
        print(f"kvstore={kv.type} workers={kv.num_workers} mode={name} "
              f"payload={args.data_mb:.0f}MB x{args.iters} "
              f"time={dt:.3f}s bandwidth={results[name]:.2f} GB/s")
    if results.get("per-key"):
        print(f"batched/per-key speedup: "
              f"{results['batched'] / results['per-key']:.2f}x")
    import json
    print("BWJSON " + json.dumps({
        "kvstore": kv.type, "workers": kv.num_workers,
        "wire": getattr(kv, "_wire_mode", None),
        "compression": args.gc_type,
        "batched_gb_s": round(results["batched"], 3),
        "per_key_gb_s": round(results.get("per-key", 0.0), 3)}))
    return results["batched"]


if __name__ == "__main__":
    main()
