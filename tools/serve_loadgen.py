#!/usr/bin/env python
"""Serving load generator: continuous vs static batching on the compiled
inference engine (mxnet_tpu.serving), with p50/p99 latency, tokens/s,
and batch occupancy — the ISSUE 7 serving benchmark.

The request mix is DETERMINISTIC (prompt lengths and generation budgets
cycle through fixed lists), so the policy comparison — tokens-per-step
and occupancy — is exact and CI-gateable; walltime-derived numbers
(tokens/s, p50/p99) ride along as evidence, never as gates.

Usage:
  python tools/serve_loadgen.py --smoke           # CPU-sized, tier-1
  python tools/serve_loadgen.py --requests 64 --max-batch 8
  python tools/serve_loadgen.py --mode continuous|static|both
  python tools/serve_loadgen.py --smoke --replicas 2   # router fleet:
      shared-system-prompt mix through N replicas (prefix cache +
      chunked prefill on), reporting prefix hit rate and per-replica
      occupancy (ISSUE 12)
  python tools/serve_loadgen.py --smoke --speculative  # draft/verify
      decoding on the continuous policy (outputs bitwise unchanged;
      reports acceptance rate + tokens per dispatch, ISSUE 17)
  python tools/serve_loadgen.py --smoke --disagg --replicas 4  # split
      the fleet into prefill/decode pools over one shared KV pool,
      reporting handoffs + per-pool occupancy (ISSUE 18)
  python tools/serve_loadgen.py --smoke --replicas 2 --tp 2  # shard
      every replica's weights + KV pool on a tp submesh (ISSUE 18;
      outputs bitwise unchanged)
  python tools/serve_loadgen.py --smoke --kv-dtype fp8  # store the
      paged KV pool in fp8 with per-row amax scales (ISSUE 20):
      reports kv_capacity_ratio (blocks an equal byte budget holds vs
      f32 — pure pool arithmetic, real on CPU) and kv_decode_drift
      (max |logit| gap of a short greedy decode vs an explicit
      fp32-KV engine on the same weights)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# smoke mix: mixed prompt lengths + mixed generation budgets — the
# shape of traffic where continuous batching wins (short requests vacate
# slots that static batching would leave idle)
_PROMPT_MIX = (5, 12, 24, 8, 17, 3)
_NEW_MIX = (4, 12, 6, 16, 3, 9)
# router mix: every request opens with the SAME system prompt (the
# millions-of-users shape) — deterministic, so the prefix hit rate and
# the computed-token savings are exact, CI-gateable quantities
_SYS_PROMPT_LEN = 12
_USER_MIX = (5, 9, 3, 7, 4, 11)


def _build_net(smoke):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                     LlamaForCausalLM)
    if smoke:
        cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=128, max_seq_len=128,
                          tie_embeddings=True)
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          num_layers=8, num_heads=16, num_kv_heads=8,
                          intermediate_size=2816, max_seq_len=1024)
    net = LlamaForCausalLM(cfg)
    net.initialize()
    net(mx.nd.array(np.zeros((1, 4), np.int32)))   # materialize shapes
    net.hybridize()
    return net, cfg


def _requests(n, vocab, seed=0):
    import numpy as np
    from mxnet_tpu.serving import Request
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        t = _PROMPT_MIX[i % len(_PROMPT_MIX)]
        new = _NEW_MIX[i % len(_NEW_MIX)]
        out.append(Request(rng.randint(0, vocab, (t,)).tolist(), new))
    return out


def _kv_capacity_ratio(cfg, kv_dtype, block_size):
    """Blocks an equal byte budget holds under ``kv_dtype`` vs f32 —
    pure pool arithmetic (ISSUE 20), so it is REAL on a CPU run.  The
    budget is what 256 f32 blocks of this model's KV geometry cost;
    fp8 pays its per-row f32 scale planes out of the same budget."""
    from mxnet_tpu.ops.quant_kv import kv_block_bytes, kv_blocks_in_budget
    if kv_dtype is None:
        return None
    hd = cfg.hidden_size // cfg.num_heads
    geom = dict(num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
                head_dim=hd, block_size=block_size)
    budget = 256 * kv_block_bytes(**geom)
    f32 = kv_blocks_in_budget(budget, **geom)
    lowp = kv_blocks_in_budget(budget, kv_dtype=kv_dtype, **geom)
    return round(lowp / f32, 3) if f32 else None


def _kv_decode_drift(net, cfg, kv_dtype, block_size, max_context, seed):
    """Max |logit| drift of a short greedy decode under the
    low-precision KV store vs an explicit fp32-KV engine on the SAME
    weights and prompt — the ISSUE 20 serving drift evidence.  Two
    tiny single-slot engines; measured only when --kv-dtype asks."""
    import numpy as np
    from mxnet_tpu.serving import InferenceEngine
    rng = np.random.RandomState(seed + 7)
    prompt = rng.randint(0, cfg.vocab_size, (9,)).tolist()
    per_mode = []
    for kd in ("fp32", kv_dtype):
        eng = InferenceEngine(net, max_batch=1, block_size=block_size,
                              max_context=max_context, kv_dtype=kd)
        tok, _ = eng.prefill(0, prompt)
        cur = list(prompt) + [int(tok)]
        rows = []
        for _ in range(4):
            pos = len(cur) - 1
            assert eng.reserve(0, pos)
            nxt, lg = eng.decode([(0, cur[-1], pos)])
            rows.append(np.asarray(lg[0], np.float32))
            cur.append(int(nxt[0]))
        eng.release(0)
        per_mode.append(rows)
    return max(float(np.max(np.abs(a - b)))
               for a, b in zip(*per_mode))


def run_router_loadgen(n_requests=12, max_batch=4, block_size=8,
                       max_context=64, smoke=True, replicas=2, seed=0,
                       disaggregated=False, tp=0, kv_dtype=None):
    """The ISSUE 12 fleet benchmark: a deterministic shared-system-
    prompt mix through ``replicas`` engine replicas behind one Router
    (prefix cache + chunked prefill on, shared warmup compile cache,
    deterministic drive).  Returns the bench `serving` payload with the
    front-end fields measured: prefix hit rate, per-replica occupancy,
    router p50/p99.  ISSUE 18: ``disaggregated`` splits the fleet into
    prefill/decode pools over ONE shared KV pool (paged-block handoff);
    ``tp > 1`` shards every replica's weights + KV pool on a tp submesh
    (outputs bitwise unchanged either way — the benchmark measures the
    placement, not the math).  ISSUE 20: ``kv_dtype="fp8"`` stores
    every replica's KV pool quantized (capacity + drift reported)."""
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.ops.quant_kv import resolve_kv_dtype
    from mxnet_tpu.serving import InferenceEngine, Request, Router, \
        serving_block
    kv_dtype = resolve_kv_dtype(kv_dtype)
    mesh = None
    if tp and tp > 1:
        from mxnet_tpu.parallel import MeshConfig
        mesh = MeshConfig(tp=tp)
    net, cfg = _build_net(smoke)
    rng = np.random.RandomState(seed)
    sys_prompt = rng.randint(0, cfg.vocab_size,
                             (_SYS_PROMPT_LEN,)).tolist()

    # a disaggregated fleet shares ONE pool across every replica's
    # slots (plus the prefix pins), so the creator sizes it fleet-wide;
    # per-replica pools keep the engine's own default
    num_blocks = (1 + replicas * (max_batch + 1)
                  * (max_context // block_size)
                  if disaggregated else None)

    def factory(compile_cache, kv_cache=None):
        return InferenceEngine(net, max_batch=max_batch,
                               block_size=block_size,
                               max_context=max_context,
                               num_blocks=num_blocks,
                               prefill_chunk=2 * block_size,
                               prefix_cache=True, mesh=mesh,
                               compile_cache=compile_cache,
                               kv_cache=kv_cache,
                               kv_dtype=kv_dtype or "fp32")

    router = Router(factory, replicas=replicas,
                    disaggregated=disaggregated)
    for rep in router.replicas:
        if rep.role != "decode":   # decode-role replicas never prefill
            rep.engine.pin_prefix(sys_prompt)
    reqs = []
    for i in range(n_requests):
        user = rng.randint(0, cfg.vocab_size,
                           (_USER_MIX[i % len(_USER_MIX)],)).tolist()
        reqs.append(Request(sys_prompt + user,
                            _NEW_MIX[i % len(_NEW_MIX)]))
    t0 = time.perf_counter()
    for req in reqs:
        router.submit(req)
    router.drive()
    wall = time.perf_counter() - t0
    st = router.stats()
    tokens = sum(len(r.generated) for r in router.finished())
    prefix_hits = 0
    prefix_lookups = 0
    hit_tokens = 0
    computed = 0
    for rep in router.replicas:
        pc = rep.engine.prefix_cache
        prefix_hits += pc.hits
        prefix_lookups += pc.lookups
        hit_tokens += pc.hit_tokens
        computed += rep.engine.stats["prompt_tokens_computed"]
    hit_rate = prefix_hits / prefix_lookups if prefix_lookups else None
    drift = (None if kv_dtype is None else
             _kv_decode_drift(net, cfg, kv_dtype, block_size,
                              max_context, seed))
    blk = serving_block(
        max_batch=max_batch, block_size=block_size,
        buckets=_buckets(block_size, max_context),
        continuous=True, requests=st["requests"],
        p50_ms=_ms(st["p50_latency_s"]), p99_ms=_ms(st["p99_latency_s"]),
        tokens_s=(round(tokens / wall, 1) if wall > 0 else None),
        tokens_s_chip=(round(tokens / wall / replicas, 1)
                       if wall > 0 else None),
        occupancy=(sum(o) / len(o) if (o := [
            r["occupancy"] for r in st["per_replica"]
            if r["occupancy"] is not None]) else None),
        compiles_after_warmup=st["compiles_after_warmup"],
        chunked_prefill=True, router_replicas=replicas,
        prefix_hit_rate=hit_rate, router_p99_ms=_ms(st["p99_latency_s"]),
        tp_shards=(tp if tp and tp > 1 else 0),
        disaggregated=bool(st.get("disaggregated")),
        handoff_ms=(telemetry.value("serving.handoff_ms")
                    if telemetry.enabled() else None),
        prefill_pool_occupancy=st.get("prefill_pool_occupancy"),
        decode_pool_occupancy=st.get("decode_pool_occupancy"),
        kv_dtype=kv_dtype or "fp32",
        kv_capacity_ratio=_kv_capacity_ratio(cfg, kv_dtype, block_size),
        kv_decode_drift=drift)
    return {"metric": "serve_loadgen", "mode": "router",
            "smoke": bool(smoke), "serving": blk,
            "router": {
                "epoch": st["epoch"], "requeues": st["requeues"],
                "handoffs": st.get("handoffs", 0),
                "prompt_tokens_computed": computed,
                "prefix_hit_tokens": hit_tokens,
                "warmup_compiles_shared":
                    router.warmup_compiles_shared,
                "per_replica": [
                    {"rid": r["rid"], "role": r.get("role"),
                     "requests": r["requests"],
                     "occupancy": r["occupancy"]}
                    for r in st["per_replica"]],
            }}


def run_loadgen(n_requests=12, max_batch=4, block_size=8, max_context=64,
                mode="both", smoke=True, quantize=None, seed=0,
                replicas=0, speculative=False, disaggregated=False,
                tp=0, kv_dtype=None):
    """Run the mix through the chosen scheduling policy(ies); returns
    the bench `serving` payload.  ``replicas >= 1`` switches to the
    router fleet benchmark (:func:`run_router_loadgen`).
    ``speculative`` turns on draft/verify decoding for the CONTINUOUS
    policy (greedy acceptance is bitwise, so the comparison still
    measures scheduling, now in tokens-per-dispatch).
    ``disaggregated``/``tp`` are the ISSUE 18 fleet shapes (router
    benchmark only; ``disaggregated`` implies ``replicas >= 2``).
    ``kv_dtype`` (ISSUE 20) stores the paged KV pool quantized
    (``"fp8"``/``"bf16"``): the payload gains ``kv_capacity_ratio``
    (equal-byte-budget blocks vs f32) and ``kv_decode_drift`` (max
    |logit| gap vs an explicit fp32-KV engine)."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.ops.quant_kv import resolve_kv_dtype
    from mxnet_tpu.serving import (ContinuousBatcher, InferenceEngine,
                                   StaticBatcher, serving_block)
    kv_dtype = resolve_kv_dtype(kv_dtype)
    if disaggregated and replicas < 2:
        replicas = 2
    if replicas:
        return run_router_loadgen(
            n_requests=n_requests, max_batch=max_batch,
            block_size=block_size, max_context=max_context,
            smoke=smoke, replicas=replicas, seed=seed,
            disaggregated=disaggregated, tp=tp, kv_dtype=kv_dtype)
    mesh = None
    if tp and tp > 1:
        from mxnet_tpu.parallel import MeshConfig
        mesh = MeshConfig(tp=tp)
    results = {}
    paged = False
    for policy in (("continuous", "static") if mode == "both"
                   else (mode,)):
        net, cfg = _build_net(smoke)
        kw = {}
        if quantize:
            import numpy as np
            import mxnet_tpu as mx
            rng = np.random.RandomState(seed)
            kw = {"quantize": quantize,
                  "calib_data": [mx.nd.array(
                      rng.randint(0, cfg.vocab_size, (2, 16)),
                      dtype="int32") for _ in range(2)]}
        # the static baseline never drafts (its decode loop is the
        # policy under comparison), so its engine skips the verify
        # graph compiles
        engine = InferenceEngine(net, max_batch=max_batch,
                                 block_size=block_size,
                                 max_context=max_context, mesh=mesh,
                                 spec_decode=(speculative and
                                              policy == "continuous"),
                                 kv_dtype=kv_dtype or "fp32", **kw)
        paged = engine.paged_attn
        engine.warmup()
        cls = (ContinuousBatcher if policy == "continuous"
               else StaticBatcher)
        # priming pass: the first requests through a process also pay
        # one-time host-side jit warmups (key folding, conversions);
        # keep those out of the measured window so the policy
        # comparison is apples-to-apples
        prime = cls(engine)
        for req in _requests(2, cfg.vocab_size, seed + 1):
            prime.submit(req)
        prime.run()
        batcher = cls(engine)
        for req in _requests(n_requests, cfg.vocab_size, seed):
            batcher.submit(req)
        # ISSUE 9 thin-reader discipline: the measured window's compile
        # count comes off the PROCESS telemetry registry (the same
        # source a live scrape sees) as a before/after delta — the
        # registry outlives the two per-policy engines this function
        # builds.  Engine-local stats remain the fallback when the
        # telemetry kill switch is on.
        caw0 = telemetry.value("serving.compiles_after_warmup")
        t0 = time.perf_counter()
        stats = batcher.run()
        wall = time.perf_counter() - t0
        stats["wall_s"] = round(wall, 3)
        stats["tokens_s"] = round(stats["tokens_generated"] / wall, 1) \
            if wall > 0 else None
        stats["tokens_per_step"] = round(
            stats["tokens_generated"] / stats["decode_steps"], 3) \
            if stats["decode_steps"] else None
        if telemetry.enabled():
            caw1 = telemetry.value("serving.compiles_after_warmup")
            stats["compiles_after_warmup"] = (caw1 or 0) - (caw0 or 0)
            stats["cache_utilization"] = telemetry.value(
                "serving.kv_block_utilization")
        else:
            stats["compiles_after_warmup"] = \
                engine.stats["compiles_after_warmup"]
            stats["cache_utilization"] = None
        stats["ttfts"] = sorted(
            round(r.ttft(), 4) for r in batcher.finished
            if r.ttft() is not None)
        results[policy] = stats
    cont = results.get("continuous") or next(iter(results.values()))
    drift = (None if kv_dtype is None else
             _kv_decode_drift(net, cfg, kv_dtype, block_size,
                              max_context, seed))
    blk = serving_block(
        max_batch=max_batch, block_size=block_size,
        buckets=_buckets(block_size, max_context),
        quantized=bool(quantize), continuous="continuous" in results,
        requests=cont["requests"],
        p50_ms=_ms(cont.get("p50_latency_s")),
        p99_ms=_ms(cont.get("p99_latency_s")),
        ttft_p50_ms=_ms(cont["ttfts"][len(cont["ttfts"]) // 2]
                        if cont.get("ttfts") else None),
        tokens_s=cont.get("tokens_s"),
        tokens_s_chip=cont.get("tokens_s"),   # single chip here
        occupancy=cont.get("occupancy"),
        tokens_per_step=cont.get("tokens_per_step"),
        compiles_after_warmup=cont.get("compiles_after_warmup"),
        cache_utilization=cont.get("cache_utilization"),
        speculative=bool(speculative), paged_attn=paged,
        spec_accept_rate=cont.get("spec_accept_rate"),
        tokens_per_dispatch=cont.get("tokens_per_dispatch"),
        tp_shards=(tp if tp and tp > 1 else 0),
        kv_dtype=kv_dtype or "fp32",
        kv_capacity_ratio=_kv_capacity_ratio(cfg, kv_dtype, block_size),
        kv_decode_drift=drift)
    payload = {"metric": "serve_loadgen", "mode": mode,
               "smoke": bool(smoke), "serving": blk,
               "policies": {k: {kk: vv for kk, vv in v.items()
                                if kk != "ttfts"}
                            for k, v in results.items()}}
    if mode == "both":
        c, s = results["continuous"], results["static"]
        payload["continuous_vs_static"] = {
            "tokens_per_step_ratio": round(
                c["tokens_per_step"] / s["tokens_per_step"], 3)
            if s.get("tokens_per_step") else None,
            "occupancy_ratio": round(c["occupancy"] / s["occupancy"], 3)
            if s.get("occupancy") else None,
            "decode_steps": {"continuous": c["decode_steps"],
                             "static": s["decode_steps"]},
        }
    return payload


def _buckets(bs, mc):
    out = []
    b = bs
    while b <= mc:
        out.append(b)
        b *= 2
    return out


def _ms(s):
    return None if s is None else s * 1e3


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-sized model + short mix (tier-1)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=None)
    ap.add_argument("--max-context", type=int, default=None)
    ap.add_argument("--mode", choices=("continuous", "static", "both"),
                    default="both")
    ap.add_argument("--int8", action="store_true",
                    help="serve int8-quantized weights")
    ap.add_argument("--replicas", type=int, default=0,
                    help="N>=1: router fleet benchmark with a shared-"
                         "system-prompt mix (prefix cache + chunked "
                         "prefill); 0 = single-engine policy comparison")
    ap.add_argument("--speculative", action="store_true",
                    help="draft/verify decoding on the continuous "
                         "policy (greedy outputs unchanged; reports "
                         "acceptance rate + tokens per dispatch)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode fleet: split "
                         "the router replicas into prefill and decode "
                         "pools over ONE shared KV pool (paged-block "
                         "handoff; implies --replicas >= 2)")
    ap.add_argument("--tp", type=int, default=0,
                    help="N>1: shard weights + KV pool on a tp=N "
                         "submesh (outputs bitwise unchanged)")
    ap.add_argument("--kv-dtype", choices=("fp32", "bf16", "fp8"),
                    default=None,
                    help="KV-cache storage precision (ISSUE 20): fp8 "
                         "stores per-row amax-scaled codes and reports "
                         "kv_capacity_ratio (equal-byte blocks vs f32) "
                         "+ kv_decode_drift (max |logit| gap vs an "
                         "fp32-KV engine); default follows "
                         "MXTPU_KV_DTYPE")
    args = ap.parse_args(argv)
    smoke = args.smoke
    if args.tp and args.tp > 1 and smoke:
        # standalone smoke runs need the simulated device mesh; must be
        # set before the first jax import (all imports here are lazy)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    n = args.requests if args.requests is not None else (12 if smoke
                                                         else 64)
    payload = run_loadgen(
        n_requests=n, max_batch=args.max_batch,
        block_size=args.block_size or (8 if smoke else 16),
        max_context=args.max_context or (64 if smoke else 512),
        mode=args.mode, smoke=smoke,
        quantize="int8" if args.int8 else None,
        replicas=args.replicas, speculative=args.speculative,
        disaggregated=args.disagg, tp=args.tp,
        kv_dtype=args.kv_dtype)
    out = json.dumps(payload)
    if len(out) > 1800:      # the driver tail-window contract
        slim = dict(payload)
        slim.pop("policies", None)
        out = json.dumps(slim)
    print(out, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
