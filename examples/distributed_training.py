"""Distributed data-parallel training walkthrough — the reference
example/distributed_training/ pattern: dist kvstore, per-worker data shard,
identical weights on every worker after each step.

Launch a 2-worker fake cluster on one machine (reference nightly style):

    python tools/launch.py -n 2 --launcher local \
        python examples/distributed_training.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def main():
    kv = mx.kv.create("dist_sync")
    rank, size = kv.rank, kv.num_workers
    print(f"[worker {rank}] joined cluster of {size}")

    # every worker builds the same net with the same seed
    mx.random.seed(7)
    np.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.zeros((2, 16)))

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # each worker sees ITS shard of the batch (split_data by rank)
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16) * 3
    for step in range(5):
        labels = rng.randint(0, 4, 32)
        data = centers[labels] + rng.randn(32, 16) * 0.3
        shard = gluon.utils.split_data(
            mx.nd.array(data.astype(np.float32)), size, batch_axis=0)[rank]
        lshard = gluon.utils.split_data(
            mx.nd.array(labels.astype(np.float32)), size, batch_axis=0)[rank]
        with autograd.record():
            loss = loss_fn(net(shard), lshard).mean()
        loss.backward()
        trainer.step(1)
    kv.barrier()

    # weights must be bit-identical across workers after sync training
    w = net[0].weight.data().asnumpy()
    kv.init("check", mx.nd.zeros(w.shape))
    kv.pushpull("check", mx.nd.array(w / size), out=(out := mx.nd.zeros(w.shape)))
    np.testing.assert_allclose(out.asnumpy(), w, rtol=1e-5, atol=1e-6)
    print(f"[worker {rank}] weights synchronized OK")


if __name__ == "__main__":
    main()
