"""Long-context training: ring attention (context parallel) + per-layer
rematerialization + the flash-attention kernel family in one fused step.

The three levers this framework provides for sequence length (SURVEY §5.7,
task brief "long-context is first-class"):

1. **Context parallelism**: the sequence axis is sharded over the mesh 'sp'
   axis; `parallel/ring_attention.py` streams K/V blocks around the ring
   (ppermute) with exact logsumexp combination, so per-chip attention
   memory is O(T/sp * block).
2. **Flash attention**: on TPU the local block attention runs the Pallas
   kernel (`ops/flash_attention.py`) — no (T, T) score tensor, O(T*D) HBM.
3. **Rematerialization**: `model.remat(True)` wraps each decoder layer in
   jax.checkpoint, keeping only layer-boundary activations live in the
   backward — HBM scales with 1 layer, not num_layers.
4. **Blocked fused head+loss**: `net.fused_ce_loss(tokens, targets)`
   (ops/blocked_cross_entropy.py) streams the vocabulary in blocks with
   an online logsumexp — the (B, T, V) logit tensor never exists, which
   at Llama-3 scale (V=128k) is the largest single activation of the
   whole step.

Run on the virtual CPU mesh (seq 512 at toy width):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context_training.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo.nlp.llama import LlamaConfig, LlamaForCausalLM
from mxnet_tpu.parallel import make_mesh, mesh_scope
from mxnet_tpu.parallel.data_parallel import DataParallelTrainer


def main():
    import jax
    n = len(jax.devices())
    axes = {"dp": n // 4, "sp": 4} if n >= 4 else {"dp": n}
    mesh = make_mesh(axes)
    seq = 512
    print(f"mesh: {dict(mesh.shape)}  seq_len: {seq}")

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=4,
                      num_heads=4, num_kv_heads=2, intermediate_size=128,
                      max_seq_len=seq, context_parallel="sp" in axes)
    net = LlamaForCausalLM(cfg)
    net.initialize()
    net.model.remat(True)        # per-layer jax.checkpoint schedule
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    trans = rng.randint(0, 256, (256, 3))

    def sample(batch):
        out = np.zeros((batch, seq + 1), np.int32)
        out[:, 0] = rng.randint(0, 256, batch)
        for t in range(seq):
            out[:, t + 1] = trans[out[:, t], rng.randint(0, 3, batch)]
        return out

    with mesh_scope(mesh):
        trainer = DataParallelTrainer(net, loss_fn, "adam",
                                      {"learning_rate": 3e-3}, mesh=mesh)
        first = last = None
        for step in range(12):
            toks = sample(4)
            loss = trainer.step(mx.nd.array(toks[:, :-1]),
                                mx.nd.array(toks[:, 1:]))
            val = float(loss.asnumpy().mean())
            first = first if first is not None else val
            last = val
            if step % 3 == 0:
                print(f"step {step:2d}  loss {val:.4f}")
    assert last < first, (first, last)
    print(f"long-context OK: seq {seq}, ring-sp={axes.get('sp', 1)}, "
          f"remat per-layer, loss {first:.3f} -> {last:.3f}")

    # lever 4: blocked fused head+loss — same loss, no logit tensor
    from mxnet_tpu import autograd
    from mxnet_tpu.parallel import replicate_sharding
    toks = sample(2)
    # params are mesh-sharded after trainer.step; replicate the eager
    # demo inputs onto the same devices
    rep = replicate_sharding(mesh)
    tokens = mx.nd.NDArray(jax.device_put(toks[:, :-1], rep))
    targets = mx.nd.NDArray(jax.device_put(toks[:, 1:], rep))
    logits_loss = float(loss_fn(
        net(tokens).reshape((-1, cfg.vocab_size)),
        targets.reshape((-1,))).mean().asnumpy())
    with autograd.record():
        fused = net.fused_ce_loss(tokens, targets, block=64).mean()
    fused.backward()       # grads flow through the blocked head
    print(f"fused blocked CE {float(fused.asnumpy()):.4f} == "
          f"logits-path CE {logits_loss:.4f} (no (B,T,V) logits)")
    assert abs(float(fused.asnumpy()) - logits_loss) < 1e-3


if __name__ == "__main__":
    main()
