"""End-to-end SSD detection training (reference acceptance surface
``example/ssd/train.py`` / gluoncv ``train_ssd.py``, SURVEY.md §2.4).

The full reference loop on a synthetic single-object detection set:

    anchors -> contrib.MultiBoxTarget (matching + hard-negative mining)
            -> joint loss (softmax CE on cls targets with ignore mask,
               smooth-L1 on masked box offsets)
            -> gluon.Trainer step (hybridized net, one jitted program)
    eval    -> the net's inference branch: decode + in-graph box_nms
               (contrib.MultiBoxDetection) -> top-detection IoU/class check

TPU-first notes: every shape is static (fixed anchor count from the
static feature pyramid, padded labels), so train and eval each compile
to a single XLA program; the NMS is the fixed-trip-count in-graph
variant. Run on the chip it is the same program at bigger batch.

Synthetic data: each image carries ONE axis-aligned rectangle whose
class is color-coded; boxes vary in position/size. Learnable to a high
detection rate in a couple hundred steps on CPU.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo.vision.ssd import SSD
from mxnet_tpu.ndarray import contrib

nd = mx.nd


# ----------------------------------------------------------------------
# data
# ----------------------------------------------------------------------

def synthetic_batch(rng, batch, size=64, num_classes=2):
    """Images (B,3,S,S) with one color-coded rectangle each; labels
    (B,1,5) rows [cls, x0, y0, x1, y1] in [0,1] corner coords."""
    imgs = rng.uniform(0.0, 0.15, (batch, 3, size, size)).astype(np.float32)
    labels = np.full((batch, 1, 5), -1.0, np.float32)
    for i in range(batch):
        c = int(rng.randint(num_classes))
        w = float(rng.uniform(0.35, 0.6))
        h = float(rng.uniform(0.35, 0.6))
        x0 = float(rng.uniform(0.02, 0.98 - w))
        y0 = float(rng.uniform(0.02, 0.98 - h))
        xs, ys = int(x0 * size), int(y0 * size)
        xe, ye = max(xs + 2, int((x0 + w) * size)), \
            max(ys + 2, int((y0 + h) * size))
        imgs[i, c, ys:ye, xs:xe] = 1.0
        labels[i, 0] = [c, x0, y0, x0 + w, y0 + h]
    return nd.array(imgs), nd.array(labels)


# ----------------------------------------------------------------------
# model: tiny static feature pyramid + the model_zoo SSD head
# ----------------------------------------------------------------------

class TinyFeatures(gluon.HybridBlock):
    """Two-scale feature pyramid for small inputs (stride 4 and 8)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.stage1 = nn.HybridSequential()
        for ch, stride in ((16, 2), (32, 2)):
            self.stage1.add(nn.Conv2D(ch, 3, stride, 1))
            self.stage1.add(nn.Activation("relu"))
        self.stage2 = nn.HybridSequential()
        self.stage2.add(nn.Conv2D(64, 3, 2, 1))
        self.stage2.add(nn.Activation("relu"))

    def hybrid_forward(self, F, x):
        a = self.stage1(x)
        b = self.stage2(a)
        return [a, b]


def build_net(num_classes=2):
    return SSD(TinyFeatures(),
               sizes=[[0.4, 0.5], [0.6, 0.7]],
               ratios=[[1, 2, 0.5]] * 2,
               steps=[-1.0, -1.0],
               classes=[f"c{i}" for i in range(num_classes)])


# ----------------------------------------------------------------------
# loss (reference example/ssd: MultiBoxTarget -> CE + smooth-L1)
# ----------------------------------------------------------------------

class SSDLoss:
    def __init__(self):
        self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def __call__(self, cls_pred, box_pred, anchors, labels):
        # targets carry no gradient: matching/mining is a label op
        with autograd.pause():
            box_t, box_m, cls_t = contrib.MultiBoxTarget(
                anchors, labels, nd.transpose(cls_pred, (0, 2, 1)))
        valid = cls_t >= 0                       # -1 = ignored by mining
        n = cls_pred.shape[1]
        # gluon CE with sample_weight returns a per-image MEAN over
        # anchors; x n recovers the per-image SUM over kept anchors
        cls_sum = self._ce(
            cls_pred, nd.maximum(cls_t, nd.zeros_like(cls_t)), valid) * n
        loc_sum = nd.sum(
            nd.smooth_l1(box_pred.reshape((box_pred.shape[0], -1)) * box_m
                         - box_t * box_m, scalar=1.0), axis=1)
        # standard SSD normalization: (L_cls + a*L_loc) / N_matched
        num_pos = nd.maximum(nd.sum(cls_t > 0, axis=1),
                             nd.ones((cls_t.shape[0],)))
        return nd.mean((cls_sum + loc_sum) / num_pos)


# ----------------------------------------------------------------------
# eval: inference branch (decode + NMS) -> top-1 detection check
# ----------------------------------------------------------------------

def detection_accuracy(net, rng, batches=4, batch=16):
    """Fraction of images whose HIGHEST-scoring post-NMS detection has
    the right class and IoU >= 0.5 with the ground truth (a strict
    mAP proxy: with one object per image, it lower-bounds AP@0.5)."""
    hits, total = 0, 0
    for _ in range(batches):
        x, y = synthetic_batch(rng, batch)
        ids, scores, bboxes = net(x)             # eval mode: NMS output
        ids_np = ids.asnumpy()[:, :, 0]
        scores_np = scores.asnumpy()[:, :, 0]
        boxes_np = bboxes.asnumpy()
        y_np = y.asnumpy()
        for i in range(batch):
            total += 1
            order = np.argsort(-scores_np[i])
            best = next((j for j in order if ids_np[i, j] >= 0), None)
            if best is None:
                continue
            gt_cls, gx0, gy0, gx1, gy1 = y_np[i, 0]
            px0, py0, px1, py1 = boxes_np[i, best]
            ix0, iy0 = max(gx0, px0), max(gy0, py0)
            ix1, iy1 = min(gx1, px1), min(gy1, py1)
            inter = max(0.0, ix1 - ix0) * max(0.0, iy1 - iy0)
            union = ((gx1 - gx0) * (gy1 - gy0)
                     + max(0.0, px1 - px0) * max(0.0, py1 - py0) - inter)
            iou = inter / union if union > 0 else 0.0
            if int(ids_np[i, best]) == int(gt_cls) and iou >= 0.5:
                hits += 1
    return hits / max(total, 1)


# ----------------------------------------------------------------------
# training loop
# ----------------------------------------------------------------------

def train(steps=200, batch=16, lr=0.05, seed=0, log_every=25,
          hybridize=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    net = build_net()
    net.initialize(init=mx.init.Xavier())
    if hybridize:
        net.hybridize()
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    first_losses, last_losses = [], []
    t0 = time.perf_counter()
    for step in range(steps):
        x, y = synthetic_batch(rng, batch)
        with autograd.record():
            cls_pred, box_pred, anchors = net(x)
            loss = loss_fn(cls_pred, box_pred, anchors, y)
        loss.backward()
        trainer.step(batch)
        v = float(loss.asnumpy())
        (first_losses if step < 10 else last_losses).append(v)
        if log_every and (step % log_every == 0 or step == steps - 1):
            print(f"step {step:4d}  loss {v:.4f}", flush=True)
    dt = time.perf_counter() - t0
    acc = detection_accuracy(net, rng)
    first = float(np.mean(first_losses))
    last = float(np.mean(last_losses[-10:])) if last_losses else first
    print(f"loss {first:.3f} -> {last:.3f} over {steps} steps "
          f"({steps * batch / dt:.1f} img/s); "
          f"top-1 detection acc@IoU0.5 = {acc:.3f}", flush=True)
    return {"first_loss": first, "last_loss": last, "det_acc": acc,
            "img_per_sec": steps * batch / dt, "net": net}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    out = train(steps=args.steps, batch=args.batch, lr=args.lr,
                seed=args.seed)
    ok = out["last_loss"] < 0.5 * out["first_loss"] and out["det_acc"] >= 0.6
    print("SSD_TRAIN", "OK" if ok else "WEAK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
