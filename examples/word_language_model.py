"""Word-level language model (LSTM) — the reference example/gluon/
word_language_model pattern: truncated BPTT over a corpus, perplexity
metric, gradient clipping.

    python examples/word_language_model.py --num-epochs 2
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo.nlp.language_model import StandardRNN


def synthetic_corpus(vocab=200, length=20000, seed=0):
    """Markov-chain text: each token strongly predicts the next."""
    rng = np.random.RandomState(seed)
    trans = rng.randint(0, vocab, (vocab, 3))
    toks = [0]
    for _ in range(length - 1):
        toks.append(int(trans[toks[-1], rng.randint(0, 3)]))
    return np.asarray(toks, np.int32)


def batchify(corpus, batch_size):
    n = len(corpus) // batch_size
    return corpus[:n * batch_size].reshape(batch_size, n).T  # (T, B)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=20)
    ap.add_argument("--bptt", type=int, default=35)
    ap.add_argument("--lr", type=float, default=0.003)
    ap.add_argument("--clip", type=float, default=5.0)
    args = ap.parse_args()

    vocab = 200
    data = batchify(synthetic_corpus(vocab), args.batch_size)
    model = StandardRNN("lstm", vocab_size=vocab, embed_size=64,
                        hidden_size=128, num_layers=1, dropout=0.2,
                        tie_weights=False)
    model.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.num_epochs):
        total_l, n_batch = 0.0, 0
        hidden = model.begin_state(batch_size=args.batch_size)
        tic = time.time()
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt])
            y = mx.nd.array(data[i + 1:i + 1 + args.bptt])
            hidden = [h.detach() for h in hidden]
            with autograd.record():
                out, hidden = model(x, hidden)
                loss = loss_fn(out.reshape((-1, vocab)),
                               y.reshape((-1,))).mean()
            loss.backward()
            # clip_global_norm, reference gluon.utils
            gluon.utils.clip_global_norm(
                [p.grad() for p in model.collect_params().values()
                 if p.grad_req != "null"], args.clip)
            trainer.step(1)
            total_l += float(loss.asnumpy())
            n_batch += 1
        ppl = math.exp(total_l / max(n_batch, 1))
        print(f"epoch {epoch}: perplexity {ppl:.1f} "
              f"({time.time() - tic:.1f}s)")
    assert ppl < vocab / 2, "LM failed to beat uniform baseline"
    print("ok")


if __name__ == "__main__":
    main()
