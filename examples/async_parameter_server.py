"""Asynchronous parameter-server training — the reference's distinctive
``dist_async`` mode (kvstore_dist_server.h DataHandleEx): workers push
gradients at their OWN pace, the server applies the optimizer the moment
each (possibly stale) gradient arrives, and nothing on the training path
waits for stragglers.

Launch a 2-worker fake cluster on one machine:

    python tools/launch.py -n 2 --launcher local \
        python examples/async_parameter_server.py

Worker 1 deliberately runs 2x more steps than worker 0 — with dist_sync
that would deadlock at a barrier; with dist_async both make progress and
the model converges on the union of their updates.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import mod as mx_mod   # noqa: F401  (Module API also works)


def main():
    kv = mx.kv.create("dist_async")
    rank, size = kv.rank, kv.num_workers
    print(f"[worker {rank}] joined async PS cluster of {size}")

    # worker 0 owns the server; its optimizer runs SERVER-side
    if rank == 0:
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))

    # toy least-squares task: w* = [1, -2, 3]
    w_true = np.array([1.0, -2.0, 3.0], np.float32)
    rng = np.random.RandomState(100 + rank)     # DIFFERENT data per rank

    kv.init("w", mx.nd.zeros((3,)))             # worker 0's init wins
    steps = 40 if rank == 0 else 80             # deliberately uneven
    w = mx.nd.zeros((3,))
    for step in range(steps):
        kv.pull("w", out=w)                     # newest weights, no wait
        x = rng.randn(16, 3).astype(np.float32)
        y = x @ w_true
        pred = (mx.nd.array(x) * w.reshape((1, 3))).sum(axis=1)
        grad = 2.0 * (mx.nd.array(x) * (pred - mx.nd.array(y))
                      .reshape((-1, 1))).mean(axis=0)
        kv.push("w", grad)                      # applied on arrival
        if rank == 1:
            time.sleep(0.005)                   # fast worker, small naps

    kv.barrier()                                # end-of-training only
    kv.pull("w", out=w)
    err = float(np.abs(w.asnumpy() - w_true).max())
    stats = kv.push_stats()
    print(f"[worker {rank}] final w={np.round(w.asnumpy(), 3)} "
          f"max_err={err:.3f} total_pushes={stats['w']}")
    assert err < 0.15, f"async training failed to converge: {err}"
    assert stats["w"] == 120                    # every stale push applied
    print(f"[worker {rank}] ASYNC_PS_OK")


if __name__ == "__main__":
    main()
