"""Image classification with the Module API + Speedometer — the reference
example/image-classification/train_*.py pattern (SURVEY.md §2.4): symbolic
network, Module.fit, kvstore flag, Speedometer img/s logging.

    python examples/image_classification.py --network mlp --num-epochs 3
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx


def get_symbol(network, num_classes=10):
    data = mx.sym.var("data")
    if network == "mlp":
        x = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
        x = mx.sym.Activation(x, act_type="relu", name="relu1")
        x = mx.sym.FullyConnected(x, num_hidden=64, name="fc2")
        x = mx.sym.Activation(x, act_type="relu", name="relu2")
        x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc3")
    elif network == "lenet":
        x = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20,
                               name="conv1")
        x = mx.sym.Activation(x, act_type="relu", name="a1")
        x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                           name="p1")
        x = mx.sym.Convolution(x, kernel=(3, 3), num_filter=50, name="conv2")
        x = mx.sym.Activation(x, act_type="relu", name="a2")
        x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                           name="p2")
        x = mx.sym.Flatten(x, name="flat")
        x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc")
    else:
        raise ValueError(network)
    return mx.sym.SoftmaxOutput(x, name="softmax")


def synthetic_iters(network, batch_size, num_classes=10):
    rng = np.random.RandomState(0)
    n = 2000
    if network == "lenet":
        shape = (1, 28, 28)
        protos = rng.randn(num_classes, *shape) * 2
    else:
        shape = (64,)
        protos = rng.randn(num_classes, *shape) * 2
    labels = rng.randint(0, num_classes, n)
    data = protos[labels] + rng.randn(n, *shape) * 0.5
    split = int(0.9 * n)
    train = mx.io.NDArrayIter(data[:split].astype(np.float32),
                              labels[:split].astype(np.float32),
                              batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(data[split:].astype(np.float32),
                            labels[split:].astype(np.float32),
                            batch_size, label_name="softmax_label")
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    ap.add_argument("--batch-size", type=int, default=100)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--no-prefetch", action="store_true",
                    help="disable the input-prefetch thread")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    train, val = synthetic_iters(args.network, args.batch_size)
    if not args.no_prefetch:
        # overlap batch preparation with the step (the reference's
        # PrefetchingIter pattern, now backed by io.DevicePrefetcher —
        # docs/INPUT_PIPELINE.md)
        train = mx.io.PrefetchingIter(train)
    mod = mx.mod.Module(get_symbol(args.network),
                        data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            initializer=mx.init.Xavier(),
            kvstore=args.kv_store,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))
    metric = mx.metric.Accuracy()
    mod.score(val, metric)
    print("final validation:", metric.get())
    assert metric.get()[1] > 0.9, "example failed to converge"


if __name__ == "__main__":
    main()
