"""End-to-end Faster R-CNN detection training (reference acceptance
surface ``example/rcnn/train_end2end.py`` / gluoncv ``train_faster_rcnn``,
SURVEY.md §2.4).

Approximate joint training (Faster R-CNN paper §3.2), the scheme the
reference's end2end script uses — both stages in ONE backward pass:

    RPN:  anchors -> contrib.MultiBoxTarget as a 1-class matcher
          (unit variances = the RPN's raw-offset box encoding)
          -> sigmoid BCE objectness + smooth-L1 on matched anchors
    head: proposals (coordinate-detached in the net) -> per-roi
          class/box targets vs ground truth -> softmax CE + smooth-L1
          on the matched class's box column
    eval: inference branch: per-roi best class decode -> in-graph
          box_nms -> top-detection IoU/class check

TPU-first notes: static shapes end-to-end — fixed anchor grid, top-k +
fixed-trip NMS proposal selection (no dynamic-shape `contrib.Proposal`),
fixed post-NMS roi count — so train and eval each compile to a single
XLA program.

Synthetic data: ssd_train's single-rectangle set (one color-coded box
per image), learnable to a high detection rate in a few hundred steps
on CPU.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx                                    # noqa: E402
from mxnet_tpu import autograd, gluon                     # noqa: E402
from mxnet_tpu.gluon import nn                            # noqa: E402
from mxnet_tpu.gluon.model_zoo.vision.rcnn import FasterRCNN  # noqa: E402
from mxnet_tpu.ndarray import contrib                     # noqa: E402
from examples.ssd_train import synthetic_batch            # noqa: E402

nd = mx.nd

IMG_SIZE = 64


# ----------------------------------------------------------------------
# model: tiny stride-8 backbone under the model_zoo FasterRCNN
# ----------------------------------------------------------------------

class TinyBackbone(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        for ch, stride in ((16, 2), (32, 2), (64, 2)):   # stride 8 out
            self.body.add(nn.Conv2D(ch, 3, stride, 1))
            self.body.add(nn.Activation("relu"))

    def hybrid_forward(self, F, x):
        return self.body(x)


class TinyRCNN(FasterRCNN):
    """FasterRCNN wired to a small single-stage feature extractor."""

    def _features(self, x):
        return self.base(x)


def build_net(num_classes=2, post_nms=48):
    # 64px images with 22-38px objects: base 16 x scales {1.5, 2.5}
    # gives 24/40px anchors across 3 aspect ratios on the stride-8 grid
    return TinyRCNN([f"c{i}" for i in range(num_classes)],
                    backbone=TinyBackbone(), stride=8, post_nms=post_nms,
                    roi_size=(5, 5), rpn_scales=(1.5, 2.5),
                    rpn_ratios=(0.7, 1.0, 1.4), rpn_base_size=16)


# ----------------------------------------------------------------------
# loss: RPN (1-class MultiBoxTarget) + box head (per-roi matching)
# ----------------------------------------------------------------------

class RCNNLoss:
    """Joint two-stage loss on the net's train-mode outputs."""

    def __init__(self, num_classes, fg_weight=8.0):
        self._ncls = num_classes
        self._fg_w = fg_weight
        self._rpn_bce = gluon.loss.SigmoidBinaryCrossEntropyLoss(
            from_sigmoid=False)
        self._head_ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def __call__(self, cls_pred, box_pred, rois, rpn_score, rpn_loc,
                 anchors, labels01):
        b = rpn_score.shape[0]
        n_anchor = anchors.shape[1]
        n_roi = rois.shape[1]
        # anchor-order flattening must match RPN.proposals: (h, w, na)
        obj = nd.reshape(nd.transpose(rpn_score, (0, 2, 3, 1)),
                         (b, n_anchor))
        loc = nd.reshape(nd.transpose(rpn_loc, (0, 2, 3, 1)),
                         (b, n_anchor * 4))
        labels_px = nd.concat(
            labels01[:, :, 0:1], labels01[:, :, 1:5] * IMG_SIZE, dim=2)
        with autograd.pause():
            # RPN matching: objectness is detection with ONE class; unit
            # variances match the RPN's raw-offset decode (rcnn.py:
            # ox = l*aw + ax, ow = exp(l)*aw)
            rpn_lab = nd.concat(
                nd.zeros_like(labels_px[:, :, 0:1]), labels_px[:, :, 1:5],
                dim=2)
            mining_pred = nd.stack(-obj, obj, axis=1)     # (B, 2, A)
            rbox_t, rbox_m, rcls_t = contrib.MultiBoxTarget(
                anchors, rpn_lab, mining_pred,
                variances=(1.0, 1.0, 1.0, 1.0))
            # box-head matching: per-image rois vs the single gt box
            r = rois                                       # (B, R, 4) px
            gt = labels_px[:, :, 1:5]                      # (B, 1, 4)
            gcls = labels_px[:, :, 0]                      # (B, 1)
            ix0 = nd.maximum(r[:, :, 0], gt[:, :, 0])
            iy0 = nd.maximum(r[:, :, 1], gt[:, :, 1])
            ix1 = nd.minimum(r[:, :, 2], gt[:, :, 2])
            iy1 = nd.minimum(r[:, :, 3], gt[:, :, 3])
            inter = nd.maximum(ix1 - ix0, nd.zeros_like(ix0)) * \
                nd.maximum(iy1 - iy0, nd.zeros_like(iy0))
            ra = nd.maximum((r[:, :, 2] - r[:, :, 0])
                            * (r[:, :, 3] - r[:, :, 1]),
                            nd.ones_like(inter) * 1e-6)
            ga = (gt[:, :, 2] - gt[:, :, 0]) * (gt[:, :, 3] - gt[:, :, 1])
            iou = inter / (ra + ga - inter)                # (B, R)
            pos = iou >= 0.5
            # force-match: the best roi per image is positive whenever it
            # overlaps at all, so the head learns from step 0
            forced = nd.one_hot(nd.argmax(iou, axis=1), n_roi) \
                * (iou > 0.05)
            pos = nd.minimum(pos + forced, nd.ones_like(pos))
            head_cls_t = pos * (gcls + 1.0)                # 0 = background
            rw = nd.maximum(r[:, :, 2] - r[:, :, 0], nd.ones_like(ra))
            rh = nd.maximum(r[:, :, 3] - r[:, :, 1], nd.ones_like(ra))
            rx = (r[:, :, 0] + r[:, :, 2]) / 2
            ry = (r[:, :, 1] + r[:, :, 3]) / 2
            gw = gt[:, :, 2] - gt[:, :, 0]
            gh = gt[:, :, 3] - gt[:, :, 1]
            gx = (gt[:, :, 0] + gt[:, :, 2]) / 2
            gy = (gt[:, :, 1] + gt[:, :, 3]) / 2
            # decode parameterization (rcnn.py decode): variances .1/.2
            d = nd.stack((gx - rx) / rw / 0.1, (gy - ry) / rh / 0.1,
                         nd.log(nd.clip(gw / rw, 1e-3, 1e3)) / 0.2,
                         nd.log(nd.clip(gh / rh, 1e-3, 1e3)) / 0.2,
                         axis=2)                           # (B, R, 4)
        # ---- RPN losses (mean over kept anchors / matched anchors) ----
        rpn_valid = rcls_t >= 0
        rpn_cls = nd.mean(self._rpn_bce(obj, rcls_t > 0, rpn_valid)
                          * n_anchor
                          / nd.maximum(nd.sum(rpn_valid, axis=1),
                                       nd.ones((b,))))
        num_pos_a = nd.maximum(nd.sum(rcls_t > 0, axis=1), nd.ones((b,)))
        rpn_box = nd.mean(nd.sum(
            nd.smooth_l1(loc * rbox_m - rbox_t * rbox_m, scalar=3.0),
            axis=1) / num_pos_a)
        # ---- head losses ----
        flat_t = nd.reshape(head_cls_t, (b * n_roi,))
        fg = flat_t > 0
        w = nd.ones_like(flat_t) + fg * (self._fg_w - 1.0)
        head_cls = nd.mean(self._head_ce(cls_pred, flat_t, w))
        sel = nd.one_hot(nd.reshape(head_cls_t - 1.0, (b * n_roi,)),
                         self._ncls)                       # (B*R, C)
        bp = nd.reshape(box_pred, (b * n_roi, self._ncls, 4))
        bsel = nd.sum(bp * nd.expand_dims(sel, 2), axis=1)  # (B*R, 4)
        dflat = nd.reshape(d, (b * n_roi, 4))
        m = nd.expand_dims(nd.reshape(pos, (b * n_roi,)), 1)
        num_pos_r = nd.maximum(nd.sum(pos), nd.ones((1,)))
        head_box = nd.sum(nd.smooth_l1(bsel * m - dflat * m, scalar=1.0)) \
            / num_pos_r
        return rpn_cls + rpn_box + head_cls + head_box


# ----------------------------------------------------------------------
# eval: inference branch (decode + NMS) -> top-1 detection check
# ----------------------------------------------------------------------

def detection_accuracy(net, rng, batches=4, batch=16):
    """Fraction of images whose highest-scoring post-NMS detection has
    the right class and IoU >= 0.5 with the ground truth (same strict
    mAP proxy as ssd_train; boxes here are in pixels)."""
    hits, total = 0, 0
    for _ in range(batches):
        x, y = synthetic_batch(rng, batch, size=IMG_SIZE)
        ids, scores, bboxes = net(x)
        ids_np = ids.asnumpy()[:, :, 0]
        scores_np = scores.asnumpy()[:, :, 0]
        boxes_np = bboxes.asnumpy() / IMG_SIZE
        y_np = y.asnumpy()
        for i in range(batch):
            total += 1
            order = np.argsort(-scores_np[i])
            best = next((j for j in order if ids_np[i, j] >= 0), None)
            if best is None:
                continue
            gt_cls, gx0, gy0, gx1, gy1 = y_np[i, 0]
            px0, py0, px1, py1 = boxes_np[i, best]
            ix0, iy0 = max(gx0, px0), max(gy0, py0)
            ix1, iy1 = min(gx1, px1), min(gy1, py1)
            inter = max(0.0, ix1 - ix0) * max(0.0, iy1 - iy0)
            union = ((gx1 - gx0) * (gy1 - gy0)
                     + max(0.0, px1 - px0) * max(0.0, py1 - py0) - inter)
            iou = inter / union if union > 0 else 0.0
            if int(ids_np[i, best]) == int(gt_cls) and iou >= 0.5:
                hits += 1
    return hits / max(total, 1)


# ----------------------------------------------------------------------
# training loop
# ----------------------------------------------------------------------

def train(steps=300, batch=8, lr=0.002, seed=0, log_every=25,
          hybridize=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    net = build_net()
    net.initialize(init=mx.init.Xavier())
    if hybridize:
        net.hybridize()
    loss_fn = RCNNLoss(num_classes=2)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    first_losses, last_losses = [], []
    t0 = time.perf_counter()
    for step in range(steps):
        x, y = synthetic_batch(rng, batch, size=IMG_SIZE)
        with autograd.record():
            out = net(x)
            loss = loss_fn(*out, y)
        loss.backward()
        trainer.step(batch)
        v = float(loss.asnumpy())
        (first_losses if step < 10 else last_losses).append(v)
        if log_every and (step % log_every == 0 or step == steps - 1):
            print(f"step {step:4d}  loss {v:.4f}", flush=True)
    dt = time.perf_counter() - t0
    acc = detection_accuracy(net, rng)
    first = float(np.mean(first_losses))
    last = float(np.mean(last_losses[-10:])) if last_losses else first
    print(f"loss {first:.3f} -> {last:.3f} over {steps} steps "
          f"({steps * batch / dt:.1f} img/s); "
          f"top-1 detection acc@IoU0.5 = {acc:.3f}", flush=True)
    return {"first_loss": first, "last_loss": last, "det_acc": acc,
            "img_per_sec": steps * batch / dt, "net": net}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.002)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    out = train(steps=args.steps, batch=args.batch, lr=args.lr,
                seed=args.seed)
    ok = out["last_loss"] < 0.5 * out["first_loss"] and out["det_acc"] >= 0.5
    print("RCNN_TRAIN", "OK" if ok else "WEAK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
