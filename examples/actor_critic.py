"""Actor-critic on a tiny corridor environment (reference
example/gluon/actor_critic/actor_critic.py pattern: shared trunk, policy
head sampled with ``mx.nd.sample_multinomial(get_prob=True)``, REINFORCE
with a value baseline, one Trainer step per episode).

Environment (numpy, host-side like any gym): an agent starts at cell 0 of
a length-8 corridor and must reach cell 7; +1 on reaching the goal, -0.01
per step, episodes cap at 50 steps. Optimal policy = always step right.

    JAX_PLATFORMS=cpu python examples/actor_critic.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

N_CELLS, GOAL, MAX_STEPS = 8, 7, 50


class ActorCritic(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.trunk = nn.Dense(32, activation="relu")
            self.policy = nn.Dense(2)      # left / right logits
            self.value = nn.Dense(1)

    def hybrid_forward(self, F, x):
        h = self.trunk(x)
        return self.policy(h), self.value(h)


def one_hot(cell):
    v = np.zeros((1, N_CELLS), np.float32)
    v[0, cell] = 1.0
    return nd.array(v)


def run_episode(net):
    """Collect one episode; returns (log_probs, values, rewards)."""
    cell, steps = 0, 0
    log_probs, values, rewards = [], [], []
    while cell != GOAL and steps < MAX_STEPS:
        logits, value = net(one_hot(cell))
        probs = nd.softmax(logits, axis=-1)
        action, logp = nd.sample_multinomial(probs, get_prob=True)
        a = int(action.asnumpy()[0])
        cell = max(0, cell - 1) if a == 0 else min(N_CELLS - 1, cell + 1)
        steps += 1
        log_probs.append(logp[0])
        values.append(value[0, 0])
        rewards.append(1.0 if cell == GOAL else -0.01)
    return log_probs, values, rewards


def main(episodes=150, gamma=0.95, seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = ActorCritic()
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.02})
    history = []
    for ep in range(episodes):
        with autograd.record():
            log_probs, values, rewards = run_episode(net)
            returns, g = [], 0.0
            for r in reversed(rewards):
                g = r + gamma * g
                returns.append(g)
            returns.reverse()
            loss = nd.zeros((1,))
            for logp, v, ret in zip(log_probs, values, returns):
                advantage = ret - float(v.asnumpy())   # baseline, no grad
                loss = loss - logp * advantage + 0.5 * (v - ret) ** 2
        loss.backward()
        trainer.step(1)
        history.append(len(rewards))
        if (ep + 1) % 30 == 0:
            avg = sum(history[-30:]) / 30
            print(f"episode {ep + 1:3d}  avg steps (last 30): {avg:.1f}")
    early = sum(history[:30]) / 30
    late = sum(history[-30:]) / 30
    assert late < early, (early, late)
    # optimal is 7 steps; trained policy should be close
    print(f"actor-critic OK: avg steps {early:.1f} -> {late:.1f} "
          f"(optimal {GOAL})")


if __name__ == "__main__":
    main()
