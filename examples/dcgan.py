"""DCGAN — the reference example/gluon/dcgan.py pattern: generator with
Conv2DTranspose, discriminator with strided convs, alternating G/D steps.

    python examples/dcgan.py --num-iters 30
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def build_generator(ngf=16, nc=1):
    netG = nn.HybridSequential()
    # latent (B, z, 1, 1) -> (B, nc, 16, 16)
    netG.add(nn.Conv2DTranspose(ngf * 2, 4, 1, 0, use_bias=False))
    netG.add(nn.BatchNorm())
    netG.add(nn.Activation("relu"))
    netG.add(nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False))
    netG.add(nn.BatchNorm())
    netG.add(nn.Activation("relu"))
    netG.add(nn.Conv2DTranspose(nc, 4, 2, 1, use_bias=False))
    netG.add(nn.Activation("tanh"))
    return netG


def build_discriminator(ndf=16):
    netD = nn.HybridSequential()
    netD.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False))
    netD.add(nn.LeakyReLU(0.2))
    netD.add(nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False))
    netD.add(nn.BatchNorm())
    netD.add(nn.LeakyReLU(0.2))
    netD.add(nn.Conv2D(1, 4, 1, 0, use_bias=False))
    netD.add(nn.Flatten())
    return netD


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-iters", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--nz", type=int, default=8)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    # "real" data: smooth blobs, 16x16 grayscale in [-1, 1]
    yy, xx = np.mgrid[0:16, 0:16] / 15.0

    def real_batch(n):
        cx = rng.uniform(0.3, 0.7, (n, 1, 1))
        cy = rng.uniform(0.3, 0.7, (n, 1, 1))
        img = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / 0.05))
        return mx.nd.array((img * 2 - 1)[:, None].astype(np.float32))

    netG = build_generator(nc=1)
    netD = build_discriminator()
    netG.initialize(init=mx.init.Normal(0.02))
    netD.initialize(init=mx.init.Normal(0.02))
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": 2e-4, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": 2e-4, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    b = args.batch_size
    real_label = mx.nd.ones((b,))
    fake_label = mx.nd.zeros((b,))
    for it in range(args.num_iters):
        # D step
        noise = mx.nd.random.normal(shape=(b, args.nz, 1, 1))
        real = real_batch(b)
        with autograd.record():
            out_real = netD(real).reshape((-1,))
            err_real = loss_fn(out_real, real_label)
            fake = netG(noise)
            out_fake = netD(fake.detach()).reshape((-1,))
            err_fake = loss_fn(out_fake, fake_label)
            errD = (err_real + err_fake).mean()
        errD.backward()
        trainerD.step(1)
        # G step
        with autograd.record():
            fake = netG(noise)
            out = netD(fake).reshape((-1,))
            errG = loss_fn(out, real_label).mean()
        errG.backward()
        trainerG.step(1)
        if it % 10 == 0:
            print(f"iter {it}: D {float(errD.asnumpy()):.3f} "
                  f"G {float(errG.asnumpy()):.3f}")
    img = netG(mx.nd.random.normal(shape=(1, args.nz, 1, 1)))
    assert img.shape == (1, 1, 16, 16)
    assert np.isfinite(errD.asnumpy()).all() and \
        np.isfinite(errG.asnumpy()).all()
    print("ok: generated", img.shape)


if __name__ == "__main__":
    main()
