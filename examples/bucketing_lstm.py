"""Bucketed LSTM language model — the reference ``example/rnn`` workflow.

The classic reference pipeline, end to end on the TPU rebuild:
``mx.rnn.BucketSentenceIter`` (variable-length sentences, bucketed +
padded) feeding ``mx.mod.BucketingModule`` whose per-bucket symbol uses
the FUSED ``mx.sym.RNN`` op (packed parameter vector, the cuDNN-RNN
surface — here one lax.scan per direction compiled by XLA).

Synthetic corpus: each sentence is a ramp t, t+1, t+2, ... (mod V), so
next-token prediction is exactly learnable; training drives per-token
accuracy from ~1/V to >0.9.

Run: PYTHONPATH= JAX_PLATFORMS=cpu python examples/bucketing_lstm.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402

VOCAB, EMBED, HIDDEN = 20, 16, 32
BATCH, BUCKETS = 8, [6, 10, 14]
GATES = 4   # lstm


def make_corpus(n=160, seed=0):
    rng = np.random.RandomState(seed)
    sentences = []
    for _ in range(n):
        length = rng.choice([5, 6, 9, 10, 13, 14])
        start = rng.randint(0, VOCAB)
        sentences.append([(start + t) % VOCAB for t in range(length)])
    return sentences


def sym_gen(seq_len):
    """Per-bucket symbol; all buckets share every parameter (embedding,
    packed LSTM vector, output FC) because the names match."""
    n_params = (GATES * HIDDEN * EMBED      # W_i2h
                + GATES * HIDDEN * HIDDEN   # W_h2h
                + 2 * GATES * HIDDEN)       # b_i2h, b_h2h
    data = mx.sym.var("data")
    label = mx.sym.var("softmax_label")
    emb = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                           name="embed")
    seq = mx.sym.transpose(emb, axes=(1, 0, 2))     # (T, B, E) seq-major
    par = mx.sym.var("lstm_params", shape=(n_params,))
    h0 = mx.sym.zeros(shape=(1, BATCH, HIDDEN))
    c0 = mx.sym.zeros(shape=(1, BATCH, HIDDEN))
    out = mx.sym.RNN(seq, par, h0, c0, state_size=HIDDEN, num_layers=1,
                     mode="lstm", name="lstm")      # (T, B, H)
    flat = mx.sym.reshape(out, shape=(-1, HIDDEN))
    logits = mx.sym.FullyConnected(flat, num_hidden=VOCAB, name="pred")
    lab = mx.sym.reshape(mx.sym.transpose(label), shape=(-1,))
    # padding positions carry label -1: use_ignore zeroes their gradient
    sm = mx.sym.SoftmaxOutput(logits, lab, use_ignore=True,
                              ignore_label=-1, name="softmax")
    return sm, ("data",), ("softmax_label",)


def token_accuracy(mod, it):
    """Per-token next-token accuracy over one pass (padding excluded)."""
    correct = total = 0
    it.reset()
    for batch in it:
        mod.forward(batch, is_train=False)   # auto bucket switch
        probs = mod.get_outputs()[0].asnumpy()      # (T*B, V)
        labels = batch.label[0].asnumpy().T.reshape(-1)
        mask = labels >= 0
        pred = probs.argmax(axis=1)
        correct += int((pred[mask] == labels[mask]).sum())
        total += int(mask.sum())
    return correct / max(total, 1)


def main():
    corpus = make_corpus()
    train_it = mx.rnn.BucketSentenceIter(corpus, BATCH, buckets=BUCKETS)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=max(BUCKETS))
    mod.bind(data_shapes=train_it.provide_data,
             label_shapes=train_it.provide_label, for_training=True)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.02})

    acc0 = token_accuracy(mod, train_it)
    for epoch in range(6):
        train_it.reset()
        for batch in train_it:
            mod.forward_backward(batch)      # auto bucket switch
            mod.update()
        print(f"epoch {epoch} done")
    acc = token_accuracy(mod, train_it)
    assert acc > 0.9, f"bucketed LSTM failed to learn the ramp: {acc}"
    assert acc > acc0 + 0.5
    print(f"bucketing LSTM OK: accuracy {acc0:.3f} -> {acc:.3f}")


if __name__ == "__main__":
    main()
