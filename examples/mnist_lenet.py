"""LeNet on MNIST via Gluon — the reference example/gluon/mnist/mnist.py
pattern (SURVEY.md §2.4: the PR1 acceptance flow), running on the TPU rebuild.
Uses synthetic MNIST when real idx files are absent (MXTPU_SYNTHETIC_DATA=1)."""
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.vision import datasets, transforms

mx.random.seed(0)
np.random.seed(0)

# LeNet
net = nn.HybridSequential()
net.add(nn.Conv2D(6, kernel_size=5, activation='relu'),
        nn.MaxPool2D(2),
        nn.Conv2D(16, kernel_size=3, activation='relu'),
        nn.MaxPool2D(2),
        nn.Flatten(),
        nn.Dense(120, activation='relu'),
        nn.Dense(84, activation='relu'),
        nn.Dense(10))
ctx = mx.cpu()
net.initialize(init=mx.init.Xavier(), ctx=ctx)
net.hybridize()

to_tensor = transforms.ToTensor()
train_ds = datasets.MNIST(train=True, synthetic=True, size=2000).transform_first(lambda d: to_tensor(d))
val_ds = datasets.MNIST(train=False, synthetic=True, size=500).transform_first(lambda d: to_tensor(d))
# prefetch_to_device: a worker thread ships batch N+1 to the device
# while the step consumes batch N (docs/INPUT_PIPELINE.md); batches
# arrive device-resident, and Trainer.step below runs the donated
# fused group update automatically
train_loader = gluon.data.DataLoader(train_ds, batch_size=100, shuffle=True,
                                     prefetch_to_device=True)
val_loader = gluon.data.DataLoader(val_ds, batch_size=100)

trainer = gluon.Trainer(net.collect_params(), 'sgd',
                        {'learning_rate': 0.01, 'momentum': 0.9})
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
metric = mx.metric.Accuracy()

for epoch in range(8):
    metric.reset()
    for data, label in train_loader:
        # already device-resident via prefetch_to_device
        with autograd.record():
            out = net(data)
            L = loss_fn(out, label)
        L.backward()
        trainer.step(data.shape[0])
        metric.update(label, out)
    name, train_acc = metric.get()
    metric.reset()
    for data, label in val_loader:
        metric.update(label, net(data))
    _, val_acc = metric.get()
    print(f"epoch {epoch}: train {name}={train_acc:.3f} val={val_acc:.3f}")

assert val_acc > 0.95, f"did not converge: {val_acc}"
net.save_parameters('/tmp/lenet.params')
net2 = nn.HybridSequential()
net2.add(nn.Conv2D(6, kernel_size=5, activation='relu'), nn.MaxPool2D(2),
         nn.Conv2D(16, kernel_size=3, activation='relu'), nn.MaxPool2D(2),
         nn.Flatten(), nn.Dense(120, activation='relu'),
         nn.Dense(84, activation='relu'), nn.Dense(10))
net2.load_parameters('/tmp/lenet.params')
x0, y0 = next(iter(val_loader))
assert np.allclose(net(x0).asnumpy(), net2(x0).asnumpy(), atol=1e-5)
print("save/load roundtrip OK; final val acc %.3f" % val_acc)
