"""Llama pretraining on a TPU mesh — the BASELINE stretch config at toy
scale: tensor parallel (megatron QKV/MLP split over 'tp') x data parallel
x context parallel (ring attention over 'sp'), one fused jitted train step.

Run on the virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/llama_pretrain.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo.nlp.llama import llama_tiny
from mxnet_tpu.parallel import make_mesh, mesh_scope
from mxnet_tpu.parallel.data_parallel import DataParallelTrainer


def main():
    import jax
    n = len(jax.devices())
    if n >= 8:
        axes = {"dp": n // 4, "tp": 2, "sp": 2}
    elif n >= 2:
        axes = {"dp": n // 2, "tp": 2}
    else:
        axes = {"dp": 1}
    mesh = make_mesh(axes)
    print("mesh:", dict(mesh.shape))

    net = llama_tiny(tensor_parallel="tp" in axes,
                     context_parallel="sp" in axes)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    # Markov-chain tokens so there is signal to learn
    trans = rng.randint(0, 256, (256, 3))
    def sample(batch, seq):
        out = np.zeros((batch, seq + 1), np.int32)
        out[:, 0] = rng.randint(0, 256, batch)
        for t in range(seq):
            out[:, t + 1] = trans[out[:, t], rng.randint(0, 3, batch)]
        return out

    batch = max(4, 2 * axes.get("dp", 1))
    with mesh_scope(mesh):
        trainer = DataParallelTrainer(net, loss_fn, "adam",
                                      {"learning_rate": 3e-3}, mesh=mesh)
        first = last = None
        for step in range(30):
            toks = sample(batch, 32)
            loss = trainer.step(mx.nd.array(toks[:, :-1]),
                                mx.nd.array(toks[:, 1:]))
            val = float(loss.asnumpy().mean())
            first = first if first is not None else val
            last = val
            if step % 10 == 0:
                print(f"step {step}: loss {val:.3f}")
    print(f"loss {first:.3f} -> {last:.3f}")
    assert last < first, "pretraining loss did not decrease"
    print("ok")


if __name__ == "__main__":
    main()
