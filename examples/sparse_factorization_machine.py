"""Factorization machine on sparse features — the reference's sparse
showcase (SURVEY.md §2.1 sparse rows + §2.5 sparse/embedding parallel):
row_sparse embedding gradients with a host parameter server
(parallel/ps.py EmbeddingPS) pulling only the touched rows.

    python examples/sparse_factorization_machine.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.parallel.ps import EmbeddingPS


def main():
    num_features = 1000        # sparse one-hot vocabulary
    dim = 8                    # factorization rank
    batch = 64
    steps = 120
    active = 5                 # non-zeros per example

    rng = np.random.RandomState(0)
    # ground truth: score = sum_i w[i] over active features, threshold
    true_w = rng.randn(num_features) * 0.5

    ps_v = EmbeddingPS(num_features, dim, optimizer="adagrad")
    ps_w = EmbeddingPS(num_features, 1, optimizer="adagrad")

    losses = []
    for step in range(steps):
        feats = rng.randint(0, num_features, (batch, active))
        y = (true_w[feats].sum(1) > 0).astype(np.float32)

        # host PS: pull only the touched embedding rows (row_sparse_pull
        # returns the row slab, the unique ids, and per-example local ids)
        v_rows, uniq, inv = ps_v.row_sparse_pull(feats)   # (U, dim)
        w_rows, _, _ = ps_w.row_sparse_pull(feats)        # (U, 1)
        v_rows.attach_grad()
        w_rows.attach_grad()
        idx = inv

        n_uniq = v_rows.shape[0]
        with autograd.record():
            v = mx.nd.Embedding(idx, v_rows, input_dim=n_uniq,
                                output_dim=dim)      # (B, A, dim)
            w = mx.nd.Embedding(idx, w_rows, input_dim=n_uniq,
                                output_dim=1)        # (B, A, 1)
            linear = w.sum(axis=1).reshape((-1,))
            # FM second order: 0.5 * ((sum v)^2 - sum v^2)
            sv = v.sum(axis=1)
            s2 = (v * v).sum(axis=1)
            pair = 0.5 * (sv * sv - s2).sum(axis=-1)
            logits = linear + pair
            loss = mx.nd.log(1 + mx.nd.exp(-(2 * mx.nd.array(y) - 1) *
                                           logits)).mean()
        loss.backward()
        # push sparse grads back: only touched rows update on the server
        ps_v.push(uniq, v_rows.grad.asnumpy(), lr=0.3)
        ps_w.push(uniq, w_rows.grad.asnumpy(), lr=0.3)
        losses.append(float(loss.asnumpy()))
        if step % 20 == 0:
            print(f"step {step}: logloss {losses[-1]:.4f}")

    print(f"logloss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0] * 0.9, "FM failed to learn"
    print("ok")


if __name__ == "__main__":
    main()
