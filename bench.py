"""Headline benchmark: ResNet-50 fused training step, images/sec.

Mirrors the reference's headline number (BASELINE.md: ResNet-50 v1 training
throughput, ~380 img/s/GPU fp32 on V100 from docs/faq/perf.md). Here the
whole record->forward->backward->update loop is ONE jitted XLA program
(SURVEY.md §3.2 TPU mapping) on whatever accelerator jax exposes.

Robustness contract (VERDICT r1 #1): this script ALWAYS prints exactly one
JSON line and exits 0. TPU backend bring-up is probed in a subprocess with a
timeout + retry/backoff (a wedged axon tunnel hangs jax.devices() forever,
so an in-process probe can't be trusted); on persistent failure it falls
back to CPU and records the failure in an "error" field.

Prints ONE JSON line:
  {"metric": "resnet50_train_images_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N/380}
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S = 380.0  # ResNet-50 v1 fp32 per-V100 (BASELINE.md)

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "v = jnp.ones((128, 128)) @ jnp.ones((128, 128));"
    "v.block_until_ready();"
    "print('PROBE_OK', d[0].platform)"
)


def _probe_backend(timeout: float) -> str | None:
    """Bring up the default JAX backend in a throwaway subprocess.

    Returns the platform name on success, None on failure/timeout. Keeps
    the wedged-tunnel failure mode out of this process entirely.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        return r.stdout.split("PROBE_OK", 1)[1].strip().split()[0]
    return None


def _force_cpu() -> None:
    """Strip the axon sitecustomize and pin this process to CPU JAX
    (shared defense — see ``_cpu_defense.py``)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _cpu_defense import force_cpu
    force_cpu()


def _cpu_fallback_subprocess(timeout: float = 900.0) -> dict | None:
    """Re-run this benchmark on CPU in a fresh subprocess.

    A process whose JAX backend is already initialized cannot be switched to
    CPU in-place (xla_bridge caches live backends), so the fallback must be
    a clean interpreter with the sitecustomize stripped from PYTHONPATH.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (ValueError, TypeError):
            continue
    return None


def _run_bench() -> dict:
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "128"))
    iters = int(os.environ.get("MXTPU_BENCH_ITERS", "20"))
    warmup = int(os.environ.get("MXTPU_BENCH_WARMUP", "3"))
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bf16")

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # CPU smoke config so the bench is runnable anywhere
        batch = min(batch, 16)
        iters = min(iters, 5)

    if dtype == "bf16":
        # MXU-native mixed precision: conv/matmul inputs cast to bfloat16,
        # softmax/norms in fp32 (mx.amp op lists); compiled into the step
        from mxnet_tpu import amp
        amp.init(target_dtype="bfloat16")

    net = resnet50_v1()
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = DataParallelTrainer(net, loss_fn, "sgd",
                                  {"learning_rate": 0.1, "momentum": 0.9},
                                  mesh=mesh)

    data = mx.nd.random.uniform(shape=(batch, 3, 224, 224))
    label = mx.nd.zeros((batch,))

    for _ in range(max(warmup, 1)):
        loss = trainer.step(data, label)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(data, label)
    loss.asnumpy()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    return {
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "platform": platform,
        "batch": batch,
        "dtype": dtype,
    }


def main() -> int:
    attempts = int(os.environ.get("MXTPU_BENCH_PROBE_ATTEMPTS", "3"))
    timeout = float(os.environ.get("MXTPU_BENCH_PROBE_TIMEOUT", "180"))
    error = None

    platform = None
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # explicitly CPU-pinned: nothing to probe, but still strip the axon
        # plugin — a wedged tunnel can hang backend discovery even when the
        # requested platform is cpu (same defense as tests/conftest.py)
        platform = "cpu"
        _force_cpu()
    else:
        for i in range(attempts):
            platform = _probe_backend(timeout)
            if platform is not None:
                break
            if i < attempts - 1:
                time.sleep(min(5.0 * (i + 1), 15.0))
    if platform is None:
        error = (f"backend probe failed after {attempts} attempts "
                 f"({timeout:.0f}s timeout each); falling back to CPU")
        _force_cpu()

    try:
        result = _run_bench()
    except Exception as e:  # noqa: BLE001 — always emit the JSON line
        err = f"bench failed on {platform}: {type(e).__name__}: {e}"
        error = err if error is None else f"{error}; then {err}"
        result = None
        if platform != "cpu":
            # accelerator bench died mid-run: a fresh CPU subprocess still
            # gets the driver a parseable number (in-process backend switch
            # is impossible once jax initialized the accelerator)
            result = _cpu_fallback_subprocess()
        if result is None:
            result = {"metric": "resnet50_train_images_per_sec",
                      "value": 0.0, "unit": "img/s", "vs_baseline": 0.0}
    if error is not None:
        result["error"] = error
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
