"""Headline benchmarks: ResNet-50 img/s and BERT-base samples/sec.

Mirrors the reference's headline numbers (BASELINE.md): ResNet-50 v1
training throughput (~380 img/s/GPU fp32 on V100, docs/faq/perf.md) and
GluonNLP BERT-base samples/sec.  The whole record->forward->backward->update
loop is ONE jitted XLA program (SURVEY.md §3.2 TPU mapping) on whatever
accelerator jax exposes.  BERT's attention runs through the Pallas
flash-attention kernel (ops/flash_attention.py) and the bench records a
numerics cross-check + timing vs the lax.scan fallback as evidence the
kernel actually executed.

MFU: each result carries XLA's own cost-analysis FLOP count for the
compiled step (fallback: analytic 2*MAC estimate) divided by the chip's
advertised bf16 peak.

Env knobs: MXTPU_BENCH_MODEL=all|resnet50|bert, MXTPU_BENCH_BATCH,
MXTPU_BENCH_BERT_BATCH, MXTPU_BENCH_SEQ, MXTPU_BENCH_ITERS,
MXTPU_BENCH_DTYPE, MXTPU_BENCH_DATA=synthetic|rec (ResNet input pipeline
on the clock), MXTPU_BENCH_PROFILE=1 (dump mx.profiler trace).

Robustness contract (VERDICT r1 #1): this script ALWAYS prints at least one
JSON line and exits 0; the LAST line is the headline ResNet number (driver
parses the last line; BERT result is both its own earlier line and the
"extra.bert" field of the last).  TPU bring-up is probed in a subprocess
with timeout+retry (a wedged axon tunnel hangs jax.devices() forever); on
persistent failure it falls back to CPU with a loud "cpu-fallback" platform
marker (VERDICT r2 weak #8).  ONE exception, by explicit opt-in:
MXTPU_BENCH_REQUIRE_TPU=1 turns a non-TPU backend into a fail-fast exit 2
(still prints its JSON + compact lines) — no CPU fallback numbers exist to
be misread (the r04/r05 lesson).  Every run stamps platform_requested /
platform_actual in the payload either way.
"""
from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

BASELINE_RESNET_IMG_S = 380.0   # ResNet-50 v1 fp32 per-V100 (BASELINE.md)
BASELINE_BERT_SAMPLES_S = 60.0  # provisional: GluonNLP-era BERT-base V100
                                # finetune samples/s (BASELINE.md row 3 has
                                # no canonical in-repo number)

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "v = jnp.ones((128, 128)) @ jnp.ones((128, 128));"
    "v.block_until_ready();"
    "print('PROBE_OK', d[0].platform)"
)


def _probe_backend(timeout: float) -> str | None:
    """Bring up the default JAX backend in a throwaway subprocess.

    Returns the platform name on success, None on failure/timeout. Keeps
    the wedged-tunnel failure mode out of this process entirely.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        return r.stdout.split("PROBE_OK", 1)[1].strip().split()[0]
    return None


def _force_cpu() -> None:
    """Strip the axon sitecustomize and pin this process to CPU JAX
    (shared defense — see ``_cpu_defense.py``)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _cpu_defense import force_cpu
    force_cpu()


def _cpu_fallback_subprocess(timeout: float = 900.0) -> dict | None:
    """Re-run this benchmark on CPU in a fresh subprocess.

    A process whose JAX backend is already initialized cannot be switched to
    CPU in-place (xla_bridge caches live backends), so the fallback must be
    a clean interpreter with the sitecustomize stripped from PYTHONPATH.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    env["MXTPU_BENCH_CPU_SMOKE"] = "1"   # placeholder numbers, keep it quick
    # the child must NOT append its compact headline: this parser takes the
    # LAST json line, and the parent re-compacts (and re-prints) anyway
    env["MXTPU_BENCH_NO_COMPACT"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (ValueError, TypeError):
            continue
    return None


# ---------------------------------------------------------------------------
# MFU helpers — lifted into mxnet_tpu/telemetry/costmodel.py (ISSUE 14)
# so the trainer's live `train.mfu` gauge and bench's offline numbers
# share ONE cost model.  The bench-local names stay as lazy wrappers
# (mxnet_tpu must not import before the backend probe decides the
# platform); output for the same inputs is byte-identical
# (test_bench_line.py).
# ---------------------------------------------------------------------------

def _costmodel():
    from mxnet_tpu.telemetry import costmodel
    return costmodel


def _chip_peak_flops(dev) -> float | None:
    return _costmodel().chip_peak_flops(dev)


def _compiled_flops(jitted, *args) -> float | None:
    return _costmodel().compiled_flops(jitted, *args)


def _resnet_train_flops_per_img() -> float:
    return _costmodel().resnet_train_flops_per_img()


def _bert_train_flops_per_sample(seq, layers=12, d=768,
                                 ffn=3072) -> float:
    return _costmodel().bert_train_flops_per_sample(seq, layers=layers,
                                                    d=d, ffn=ffn)


def _attach_mfu(result, flops_per_sample, samples_per_sec, jitted=None,
                jit_args=None):
    return _costmodel().attach_mfu(result, flops_per_sample,
                                   samples_per_sec, jitted=jitted,
                                   jit_args=jit_args)


def _stamp_live_mfu(result: dict) -> dict:
    """Attach the trainer-published live gauge (`train.mfu` as
    ``mfu_live``): measured during the timed loop itself, null when the
    chip peak is unknown (CPU) or telemetry is off — never a fake
    zero (the PR 6 honesty rule)."""
    from mxnet_tpu import telemetry as _telem
    result["mfu_live"] = _telem.value("train.mfu")
    return result


# ---------------------------------------------------------------------------
# ResNet-50
# ---------------------------------------------------------------------------

def _overlap_probe(trainer, feeder, iters, batch) -> dict:
    """Run the overlapped pipeline end-to-end: fresh .rec decode ->
    DevicePrefetcher H2D (double-buffered, worker thread) -> donated
    fused train step, all three stages concurrent.  Returns per-stage
    timings + ``overlap_efficiency`` + ``img_s_overlapped`` for the
    ``input_pipeline`` block (ISSUE 2 tentpole instrumentation)."""
    from mxnet_tpu.io import DevicePrefetcher

    # compile the plain-batch step off the clock (the timed rec loop
    # above used the indexed-epoch entry point)
    d0, l0 = feeder._batches[0]
    loss = trainer.step(d0, l0[: len(d0)].astype("float32"))
    loss.asnumpy()
    pf = DevicePrefetcher(feeder.stream(iters), depth=2,
                          mesh=trainer.mesh)
    n = 0
    t0 = time.perf_counter()
    for data, label in pf:
        loss = trainer.step(data, label)
        n += 1
    loss.asnumpy()
    dt = time.perf_counter() - t0
    pf.close()
    out = pf.stats.summary()
    out["img_s_overlapped"] = round(batch * n / dt, 2)
    return out


def _bench_resnet(data_mode=None, iters=None, cost_analysis=True) -> dict:
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "128"))
    if iters is None:
        iters = int(os.environ.get("MXTPU_BENCH_ITERS", "20"))
    warmup = int(os.environ.get("MXTPU_BENCH_WARMUP", "3"))
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bf16")
    if data_mode is None:
        data_mode = os.environ.get("MXTPU_BENCH_DATA", "synthetic")

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # CPU smoke config so the bench is runnable anywhere
        batch = min(batch, 16)
        iters = min(iters, 5)

    if dtype == "bf16":
        # MXU-native mixed precision: conv/matmul inputs cast to bfloat16,
        # softmax/norms in fp32 (mx.amp op lists); compiled into the step
        from mxnet_tpu import amp
        amp.init(target_dtype="bfloat16")

    s2d = os.environ.get("MXTPU_RESNET_S2D", "1") == "1"
    net = resnet50_v1(s2d_stem=s2d)
    feeder = None
    if data_mode == "rec":
        from tools.bench_pipeline import RecBatchFeeder, wrap_preproc
        feeder = RecBatchFeeder(batch=batch)
        net = wrap_preproc(net)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    # MXTPU_BENCH_DP>1: time the ZeRO-1 sharded-sync pipeline over a dp
    # mesh (reduce-scatter + sharded update + all-gather) and measure
    # its collectives into the `comm` block; default stays the 1-chip
    # per-device number the baseline tracks
    dp = max(1, min(int(os.environ.get("MXTPU_BENCH_DP", "1")),
                    len(jax.devices())))
    if batch % dp:
        dp = 1
    mesh = make_mesh({"dp": dp}, devices=jax.devices()[:dp])
    trainer = DataParallelTrainer(net, loss_fn, "sgd",
                                  {"learning_rate": 0.1, "momentum": 0.9},
                                  mesh=mesh, shard_updates=dp > 1)

    if feeder is not None:
        # Real-data path: epoch uploaded once (timed), then per-step
        # in-graph batch indexing — see DataParallelTrainer.put_epoch.
        sd, sl = feeder.epoch_arrays()
        t0 = time.perf_counter()
        handle = trainer.put_epoch(sd, sl)
        handle[0].block_until_ready()
        h2d_dt = time.perf_counter() - t0
        n_batches = sd.shape[0]
        for k in range(max(warmup, 1)):
            loss = trainer.step_indexed(handle, k % n_batches)
        loss.asnumpy()
        t0 = time.perf_counter()
        for k in range(iters):
            loss = trainer.step_indexed(handle, k % n_batches)
        loss.asnumpy()
        dt = time.perf_counter() - t0
        feeder.stats["h2d_ms_per_epoch"] = round(h2d_dt * 1e3, 1)
        feeder.stats["h2d_gb_s"] = round(
            (sd.nbytes + sl.nbytes) / h2d_dt / 1e9, 2)
        # steady-state epoch cost = n_batches steps + one epoch upload
        dt_amort = dt + h2d_dt * iters / n_batches
        feeder.stats["img_s_incl_h2d"] = round(batch * iters / dt_amort, 2)
        # decode-pool thread scaling (VERDICT r3 #3): measured, not
        # extrapolated — on 1-core hosts it documents the host ceiling
        try:
            from tools.decode_scaling import sweep as _decode_sweep
            feeder.stats["decode_thread_sweep"] = _decode_sweep(
                n_images=256, threads=(1, 2, 4, 8), repeats=1)
            feeder.stats["host_cores"] = os.cpu_count() or 1
        except Exception as e:  # noqa: BLE001 — sweep is informational
            feeder.stats["decode_thread_sweep_error"] = str(e)
        # overlapped pipeline: decode (C++ pool) / H2D (prefetch worker)
        # / compute (consumer) run CONCURRENTLY — per-stage times and
        # overlap_efficiency land in the input_pipeline block so the
        # img_s_incl_h2d vs device-only gap is tracked per round
        try:
            feeder.stats.update(_overlap_probe(
                trainer, feeder, iters=min(iters, 10), batch=batch))
        except Exception as e:  # noqa: BLE001 — probe is evidence, not
            # a gate; the serial numbers above already stand
            feeder.stats["overlap_error"] = f"{type(e).__name__}: {e}"
    else:
        from mxnet_tpu import runtime as _rt
        k_steps = _rt.steps_per_call()
        data = mx.nd.random.uniform(shape=(batch, 3, 224, 224))
        label = mx.nd.zeros((batch,))
        for _ in range(max(warmup, 1)):
            loss = trainer.step(data, label)
        loss.asnumpy()
        if k_steps > 1:
            # multi-step compiled training (ISSUE 6): K steps scanned
            # into ONE dispatch — the host pays the dispatch/program
            # re-entry tax once per K steps
            window = [(data, label)] * k_steps
            loss = trainer.step_multi(window)      # compile off the clock
            loss.asnumpy()
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = trainer.step_multi(window)
            loss.asnumpy()
            dt = time.perf_counter() - t0
            total_steps = iters * k_steps
        else:
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = trainer.step(data, label)
            loss.asnumpy()
            dt = time.perf_counter() - t0
            total_steps = iters

    if feeder is not None:
        total_steps = iters
        k_steps = 1
    img_s = batch * total_steps / dt
    result = {
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_RESNET_IMG_S, 3),
        "platform": platform,
        "batch": batch,
        "dtype": dtype,
        "data": data_mode,
        "s2d_stem": s2d,
        "steps_per_call": k_steps,
    }
    # dispatch tax (ISSUE 6): walltime/step minus the device time/step,
    # the latter approximated by an 8-step scan window's amortized time
    # (one dispatch per window => per-step host cost ~0).  "auto" runs
    # it only on a real accelerator — an extra resnet-scan compile on a
    # CPU smoke run isn't worth the minutes; tools/bench_pipeline.py
    # dispatch_probe is the CPU-sized evidence path.
    probe_mode = os.environ.get("MXTPU_BENCH_DISPATCH_PROBE", "auto")
    result["dispatch_ms_per_step"] = None
    if feeder is None and probe_mode != "0" and \
            (probe_mode == "1" or platform == "tpu"):
        try:
            kp = 8
            window = [(data, label)] * kp
            loss = trainer.step_multi(window)
            loss.asnumpy()
            reps = max(2, min(iters, 5))
            t0 = time.perf_counter()
            for _ in range(reps):
                loss = trainer.step_multi(window)
            loss.asnumpy()
            amort_ms = (time.perf_counter() - t0) / (reps * kp) * 1e3
            per_step_ms = dt / total_steps * 1e3
            result["dispatch_ms_per_step"] = round(
                max(0.0, per_step_ms - amort_ms), 3)
        except Exception as e:  # noqa: BLE001 — probe is evidence, never
            # voids the measured throughput
            result["dispatch_probe_error"] = f"{type(e).__name__}: {e}"
    if feeder is not None:
        result["input_pipeline"] = feeder.stats
    try:
        # per-step `comm` block (parallel/zero.py schema): bytes on the
        # wire, MEASURED collective ms + est ICI GB/s when the sharded
        # pipeline runs (dp>1); zeros on CPU/dp=1 so the schema ships —
        # and is regression-tested — everywhere (tests/test_bench_line.py)
        overlap_stats = None
        if dp > 1 and os.environ.get("MXTPU_BENCH_OVERLAP_PROBE",
                                     "1") != "0":
            # with-vs-without-overlap probe (ISSUE 5): times the
            # overlapped / barrier-monolithic / compute-only builds of
            # the sharded step -> exposed_comm_ms + overlap_frac.
            # Costs three extra step compiles; MXTPU_BENCH_OVERLAP_PROBE=0
            # keeps the dp run but skips the probe on slow hosts
            if feeder is not None:
                pd, pl = mx.nd.array(sd[0]), mx.nd.array(sl[0])
            else:
                pd, pl = data, label
            overlap_stats = trainer.overlap_probe(pd, pl,
                                                  iters=min(iters, 5))
        result["comm"] = trainer.comm_stats(measure=dp > 1,
                                            step_ms=dt / iters * 1e3,
                                            overlap_stats=overlap_stats)
    except Exception as e:  # noqa: BLE001 — observability never voids the bench
        result["comm"] = {"error": f"{type(e).__name__}: {e}"}
    _stamp_parallelism(result, trainer)
    import jax.numpy as jnp
    from mxnet_tpu.ndarray import random as _rnd
    jitted = jit_args = None
    if cost_analysis and feeder is not None:
        jitted = trainer._jitted_indexed
        jit_args = (trainer._param_vals, trainer._opt_state,
                    jnp.asarray(0.1, jnp.float32), _rnd.next_key(),
                    handle[0], handle[1], jnp.asarray(0, jnp.int32))
    elif cost_analysis:
        jitted = trainer._jitted
        jit_args = (trainer._param_vals, trainer._opt_state,
                    jnp.asarray(0.1, jnp.float32), _rnd.next_key(),
                    data.data, label.data)
    _attach_mfu(result, _resnet_train_flops_per_img(), img_s, jitted,
                jit_args)
    _stamp_live_mfu(result)
    return result


# ---------------------------------------------------------------------------
# BERT-base
# ---------------------------------------------------------------------------

def _flash_evidence(batch, seq, heads=12, dhead=64) -> dict:
    """Execute the Pallas flash-attention kernel at BERT shapes; compare
    numerics + time vs the lax.scan fallback (VERDICT r2 task 1)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mxnet_tpu.ops.flash_attention import (_flash, _scan_forward,
                                               _use_pallas)

    scale = 1.0 / math.sqrt(dhead)
    rng = np.random.RandomState(7)
    shape = (batch * heads, seq, dhead)
    q, k, v = (jnp.asarray(rng.randn(*shape), jnp.bfloat16)
               for _ in range(3))

    flash_fn = jax.jit(lambda q, k, v: _flash(q, k, v, False, scale))
    scan_fn = jax.jit(
        lambda q, k, v: _scan_forward(q, k, v, False, scale,
                                      min(256, seq))[0])
    out_f = flash_fn(q, k, v).block_until_ready()
    out_s = scan_fn(q, k, v).block_until_ready()
    a = np.asarray(out_f, np.float32)
    b = np.asarray(out_s, np.float32)
    denom = max(np.max(np.abs(b)), 1e-6)
    rel = float(np.max(np.abs(a - b)) / denom)

    def _time(fn, n=20):
        fn(q, k, v).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(q, k, v)
        out.block_until_ready()
        return (time.perf_counter() - t0) / n * 1e3

    t_flash = _time(flash_fn)
    t_scan = _time(scan_fn)
    ev = {
        "pallas_kernel_used": _use_pallas(seq, seq, dhead) is not None,
        "max_rel_err_vs_scan": round(rel, 6),
        "flash_ms": round(t_flash, 3),
        "scan_ms": round(t_scan, 3),
        "speedup_vs_scan": round(t_scan / t_flash, 2) if t_flash > 0 else 0,
        "shape_bhld": [batch, heads, seq, dhead],
    }
    # bf16 tolerance: online-softmax reorders reductions; 2% envelope
    ev["numerics_ok"] = rel < 2e-2
    return ev


def _bench_bert() -> dict:
    batch = int(os.environ.get("MXTPU_BENCH_BERT_BATCH", "64"))
    seq = int(os.environ.get("MXTPU_BENCH_SEQ", "128"))
    iters = int(os.environ.get("MXTPU_BENCH_ITERS", "20"))
    warmup = int(os.environ.get("MXTPU_BENCH_WARMUP", "3"))
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bf16")

    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.nlp.bert import get_bert_model
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    platform = jax.devices()[0].platform
    if platform == "cpu":
        batch = min(batch, 4)
        seq = min(seq, 128)
        iters = min(iters, 5)

    if dtype == "bf16":
        from mxnet_tpu import amp
        amp.init(target_dtype="bfloat16")

    # dropout=0 so the flash path is live in training (the kernel has no
    # attention dropout; throughput benches conventionally disable it)
    net = get_bert_model(vocab_size=30522, max_length=seq, dropout=0.0,
                         use_flash=True, use_decoder=False)
    net.initialize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_fn(out, label):
        # out = (seq_out, pooled, cls_scores); sentence-pair head on CLS
        return ce(out[-1], label)

    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = DataParallelTrainer(net, loss_fn, "adam",
                                  {"learning_rate": 1e-4}, mesh=mesh)

    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.randint(0, 30522, size=(batch, seq)), dtype="int32")
    types = mx.nd.zeros((batch, seq), dtype="int32")
    label = mx.nd.array(rng.randint(0, 2, size=(batch,)), dtype="int32")

    for _ in range(max(warmup, 1)):
        loss = trainer.step(data, types, label)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(data, types, label)
    loss.asnumpy()
    dt = time.perf_counter() - t0

    samples_s = batch * iters / dt
    result = {
        "metric": "bert_base_train_samples_per_sec",
        "value": round(samples_s, 2),
        "unit": "samples/s",
        "vs_baseline": round(samples_s / BASELINE_BERT_SAMPLES_S, 3),
        "platform": platform,
        "batch": batch,
        "seq_len": seq,
        "dtype": dtype,
    }
    # analytic FLOPs: cross-checked against XLA cost analysis on TPU v5e
    # (77.9 vs 78.2 TFLOP/s delivered) — skips a costly AOT recompile
    _attach_mfu(result, _bert_train_flops_per_sample(seq), samples_s)
    _stamp_live_mfu(result)
    _stamp_parallelism(result, trainer)
    try:
        result["flash_attention"] = _flash_evidence(batch, seq)
    except Exception as e:  # noqa: BLE001 — evidence must not void the
        # already-measured throughput number
        result["flash_attention"] = {"error": f"{type(e).__name__}: {e}"}
    if platform == "tpu":
        # long-context point: at L>=2k the O(L^2) score tensor is what the
        # kernel exists to avoid (SURVEY §5.7); report speedup there too
        try:
            result["flash_attention_long"] = _flash_evidence(4, 2048)
        except Exception as e:  # noqa: BLE001
            result["flash_attention_long"] = {
                "error": f"{type(e).__name__}: {e}"}
    return result


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: repeat bench runs (and the
    driver's run) skip the 20-40s-per-program compiles."""
    try:
        import jax
        cache_dir = os.environ.get(
            "MXTPU_COMPILE_CACHE",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


def _kvstore_bandwidth() -> dict:
    """2-process dist_sync bandwidth (the third BASELINE metric), both
    wire paths: the in-graph XLA allreduce vs the allgather fallback.
    Runs on CPU processes (never touches the TPU)."""
    here = os.path.dirname(os.path.abspath(__file__))
    out = {}
    for mode, label in (("", "allreduce"), ("allgather", "allgather")):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = ""
        env["MXTPU_KVSTORE_WIRE"] = mode
        r = subprocess.run(
            [sys.executable, os.path.join(here, "tools", "launch.py"),
             "-n", "2", "--launcher", "local", sys.executable,
             os.path.join(here, "tools", "bandwidth", "measure.py"),
             "--kv-store", "dist_sync", "--data-mb", "32",
             "--iters", "5", "--num-keys", "8"],
            capture_output=True, text=True, timeout=300, env=env)
        for line in r.stdout.splitlines():
            if line.startswith("BWJSON "):
                out[label] = json.loads(line[7:])
                break
        else:
            out[label] = {"error": (r.stderr or r.stdout)[-300:]}
    a, g = out.get("allreduce", {}), out.get("allgather", {})
    if a.get("per_key_gb_s") and g.get("per_key_gb_s"):
        out["per_key_speedup"] = round(
            a["per_key_gb_s"] / g["per_key_gb_s"], 2)
    out["note"] = ("2 CPU procs share one host core, so the batched path "
                   "is compute-bound; allreduce wins show per-key and "
                   "grow O(workers) vs allgather")
    return out


def _tpu_bandwidth() -> dict:
    """Single-chip bandwidth numbers on the REAL device (VERDICT r3 #4a:
    'single-proc loopback is still a number'): H2D/D2H through the host
    link, HBM copy bandwidth, and the dispatch cost of a compiled psum
    over a 1-device mesh (the collective code path the pod version
    takes, minus the wire)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map

    nbytes = 64 * 1024 * 1024
    host = np.random.default_rng(0).standard_normal(
        nbytes // 4).astype(np.float32)
    out = {"payload_mb": nbytes // (1024 * 1024)}
    # H2D
    jax.device_put(host).block_until_ready()   # warm the path
    t0 = time.perf_counter()
    dev = jax.device_put(host)
    dev.block_until_ready()
    out["h2d_gb_s"] = round(nbytes / (time.perf_counter() - t0) / 1e9, 2)
    # D2H: jax.Array caches _npy_value after the first np.asarray, so the
    # timed transfers must each touch a FRESH device array
    devs = [jax.device_put(host) for _ in range(3)]
    for d in devs:
        d.block_until_ready()
    t0 = time.perf_counter()
    for d in devs:
        np.asarray(d)
    out["d2h_gb_s"] = round(
        len(devs) * nbytes / (time.perf_counter() - t0) / 1e9, 2)
    # HBM copy (read+write) via jitted identity-plus-zero
    f = jax.jit(lambda x: x + 0.0)
    f(dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10):
        y = f(dev)
    y.block_until_ready()
    dt = (time.perf_counter() - t0) / 10
    out["hbm_copy_gb_s"] = round(2 * nbytes / dt / 1e9, 2)
    # compiled psum dispatch (1-device mesh: code path, no wire)
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    g = jax.jit(shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                          in_specs=P(), out_specs=P()))
    g(dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10):
        y = g(dev)
    y.block_until_ready()
    out["psum_1dev_ms"] = round((time.perf_counter() - t0) / 10 * 1e3, 3)
    return out


def _bench_decode() -> dict:
    """Autoregressive decode throughput (tokens/s) through the Llama
    KV-cache path (gluon/model_zoo/nlp/llama.py generate(): one jitted
    lax.scan, O(T) attention against the cache).  The reference era
    served generation as repeated full forwards; this is the serving-side
    counterpart of the training headlines."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                     LlamaForCausalLM)

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:   # smoke scale
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=128, max_seq_len=128)
        batch, prefix, new = 2, 8, 16
    else:
        # ~0.5B-class decoder: big enough that the MXU/HBM balance is
        # representative, small enough to compile fast over the tunnel
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          num_layers=8, num_heads=16, num_kv_heads=8,
                          intermediate_size=2816, max_seq_len=512)
        batch, prefix, new = 8, 32, 96
    net = LlamaForCausalLM(cfg)
    net.initialize()
    toks = mx.nd.array(np.random.RandomState(0)
                       .randint(0, cfg.vocab_size, (batch, prefix)))
    net(toks)                                      # materialize params
    out = net.generate(toks, max_new_tokens=new)   # compile + warmup
    out.asnumpy()
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = net.generate(toks, max_new_tokens=new)
    out.asnumpy()
    dt = (time.perf_counter() - t0) / reps
    # generate() runs ONE scan over prefix+new steps of ~equal cost;
    # bill per STEP so prefill is not silently charged to decode
    steps = prefix + new
    return {"model": "llama-decode", "batch": batch, "prefix": prefix,
            "new_tokens": new, "hidden": cfg.hidden_size,
            "layers": cfg.num_layers,
            "tokens_per_sec": round(batch * steps / dt, 1),
            "ms_per_step": round(dt / steps * 1e3, 3),
            "note": "one jitted scan over prefix+new cache steps; "
                    "tokens/s counts all scanned positions"}


def _bench_serving() -> dict:
    """Serving-engine loadgen (ISSUE 7): continuous-batching tokens/s,
    p50/p99 request latency and batch occupancy through
    ``mxnet_tpu.serving`` + ``tools/serve_loadgen.py``.  On CPU the
    block ships the serving CONFIG with the measured fields null —
    null-when-unmeasured (the PR 6 honesty rule; the CPU-scale policy
    comparison lives in the tier-1-gated ``serve_loadgen --smoke``).
    On TPU the ~0.5B-class mix measures for real."""
    import os
    import jax
    from mxnet_tpu.serving import serving_block
    spec = os.environ.get("MXTPU_SPEC_DECODE", "0") not in ("", "0")
    paged = os.environ.get("MXTPU_PAGED_ATTN", "0") not in ("", "0")
    tp = int(os.environ.get("MXTPU_SERVE_TP", "0") or 0)
    disagg = os.environ.get("MXTPU_SERVE_DISAGG", "0") not in ("", "0")
    if jax.devices()[0].platform == "cpu":
        # config rides (speculative/paged_attn/tp_shards/disaggregated
        # are routing knobs, real either way); the measured fields —
        # including the ISSUE 18 handoff_ms / pool occupancies — stay
        # null
        blk = serving_block(max_batch=8, block_size=16,
                            buckets=(16, 32, 64, 128, 256, 512),
                            continuous=True, speculative=spec,
                            paged_attn=paged,
                            tp_shards=(tp if tp > 1 else 0),
                            disaggregated=disagg)
        blk["note"] = ("not measured on CPU; tools/serve_loadgen.py "
                      "--smoke carries the CPU policy comparison")
        return blk
    from tools.serve_loadgen import run_loadgen
    payload = run_loadgen(n_requests=32, max_batch=8, block_size=16,
                          max_context=512, mode="both", smoke=False,
                          speculative=spec, tp=tp,
                          replicas=(4 if disagg else 0),
                          disaggregated=disagg)
    blk = payload["serving"]
    blk["vs_static"] = payload.get("continuous_vs_static")
    return blk


def _bench_elastic() -> dict:
    """Elastic-membership evidence (ISSUE 8): reshard_ms / pause_ms /
    membership_epoch for one measured kill -> reshard dp N -> N/2
    transition through ``mx.elastic.ElasticController``.  On CPU the
    block ships the elastic CONFIG with the measured fields null —
    null-when-unmeasured (PR 6 honesty rule); the deterministic
    correctness/parity evidence lives in tier-1's chaos elastic suite
    (``tools/tpu_queue_runner.py --chaos elastic``).  On a multi-chip
    TPU host the transition is measured for real."""
    import jax
    from mxnet_tpu import elastic, telemetry
    devices = jax.devices()
    n = len(devices)
    if devices[0].platform == "cpu" or n < 2 or n % 2:
        blk = elastic.elastic_block(enabled=elastic.elastic_enabled(),
                                    dp=1)
        blk["note"] = ("not measured on CPU; correctness/parity "
                       "evidence: tools/tpu_queue_runner.py --chaos "
                       "elastic (tier-1, bitwise)")
        return blk
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"dp": n}, devices)
    net = gluon.nn.Dense(64)
    net.initialize()
    trainer = parallel.DataParallelTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 0.01},
        mesh=mesh, shard_updates=True)
    membership = elastic.Membership([0, 1])
    ctrl = elastic.ElasticController(
        membership, devices=devices, devices_per_worker=n // 2,
        net=net, backoff_s=0.0)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2 * n, 32).astype(np.float32))
    y = mx.nd.array(rng.randn(2 * n, 64).astype(np.float32))
    trainer.step(x, y)                       # compile + warm at dp=n
    membership.worker_dead(1)                # lose half the capacity
    ctrl.check_step(1, trainer, params=net)  # pause -> reshard -> resume
    trainer.step(x, y)                       # first post-reshard step
    blk = elastic.elastic_block(**ctrl.stats())
    if telemetry.enabled():
        # thin-reader discipline (ISSUE 9): the measured transition
        # fields come off the same registry a live scrape sees — the
        # controller published them during resync; the ISSUE 13 fields
        # (drain_ms, autoscale_decisions) stay null unless a notice
        # drain / autoscale loop actually ran
        for field, metric in (("reshard_ms", "elastic.reshard_ms"),
                              ("pause_ms", "elastic.pause_ms"),
                              ("membership_epoch", "elastic.epoch"),
                              ("drain_ms", "elastic.drain_ms"),
                              ("autoscale_decisions",
                               "autoscale.decisions")):
            v = telemetry.value(metric)
            if v is not None:
                blk[field] = v
    return blk


def _bench_fleet() -> dict:
    """Fleet-observability evidence (ISSUE 15): slowest_rank /
    step_ms_skew / scrape_ms from one ``FleetCollector.collect()`` over
    the workers named by ``MXTPU_FLEET_ADDRS`` ("h0:p0,h1:p1,...").
    A single process has no fleet to scrape — the block ships config
    with every measured field null (null-when-unmeasured, the PR 6
    honesty rule); the deterministic correctness evidence lives in the
    tier-1 chaos fleet suite (``tools/tpu_queue_runner.py --chaos
    fleet``)."""
    from mxnet_tpu.telemetry import fleet as _fleet
    addrs = os.environ.get("MXTPU_FLEET_ADDRS", "").strip()
    if not addrs:
        blk = _fleet.fleet_block(enabled=_fleet.enabled(), ranks=1)
        blk["note"] = ("single process: no fleet to scrape (set "
                       "MXTPU_FLEET_ADDRS=h0:p0,... on a pod); "
                       "correctness evidence: tools/tpu_queue_runner.py "
                       "--chaos fleet (tier-1)")
        return blk
    coll = _fleet.FleetCollector(_fleet.transports_from_addrs(addrs))
    snap = coll.collect()
    skew = snap.get("skew") or {}
    return _fleet.fleet_block(
        enabled=True, ranks=len(snap.get("ranks") or []),
        slowest_rank=skew.get("slowest_rank"),
        step_ms_skew=skew.get("skew_ratio"),
        scrape_ms=snap.get("scrape_ms"),
        stragglers=sum(1 for s in (skew.get("straggler_scores")
                                   or {}).values()
                       if s >= coll.skew),
        epoch_desync=snap.get("epoch_desync") is not None,
        scrape_dead=len(snap.get("dead") or []))


MULTIPROC_SCHEMA_VERSION = 1


def _bench_multiproc() -> dict:
    """Multi-process pod evidence (ISSUE 19): the process-level runtime
    config plus its measured recovery costs.  The bench runs in ONE
    process, so the measured fields (``coordinator_reinit_ms``,
    ``sigkill_recover_ms``) ship null unless THIS process actually went
    through a reshard (``pod.coordinator_reinit_ms`` is the gauge
    ``_dist_init.reinit_distributed`` fills via the pod worker) — the
    null-when-unmeasured honesty rule.  The correctness evidence lives
    in the real-process chaos suite (``tools/tpu_queue_runner.py
    --chaos procs``): SIGKILL mid-run, survivors at the smaller
    ``jax.process_count()``, bitwise resume from the shared
    checkpoint."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.kvstore.rpc import RetryPolicy
    import jax
    pol = RetryPolicy.from_env()
    blk = {
        "multiproc_schema_version": MULTIPROC_SCHEMA_VERSION,
        "procs": int(os.environ.get("MXTPU_NUM_PROCESSES", "1") or 1),
        "world_size": int(jax.process_count()),
        "rpc_retries": pol.retries,
        "rpc_timeout_s": pol.timeout_s,
        "coordinator_reinit_ms": None,
        "sigkill_recover_ms": None,
    }
    if telemetry.enabled():
        v = telemetry.value("pod.coordinator_reinit_ms")
        if v is not None:
            blk["coordinator_reinit_ms"] = v
        v = telemetry.value("pod.sigkill_recover_ms")
        if v is not None:
            blk["sigkill_recover_ms"] = v
    if blk["procs"] <= 1:
        blk["note"] = ("single process: recovery costs unmeasured "
                       "in-process; correctness evidence: "
                       "tools/tpu_queue_runner.py --chaos procs")
    return blk


QUANT_SCHEMA_VERSION = 1


def _bench_quant() -> dict:
    """Low-precision compute evidence (ISSUE 20): the two env knobs'
    config (``MXTPU_COMPUTE_DTYPE`` / ``MXTPU_KV_DTYPE``, real on any
    host) plus the fp8-KV capacity arithmetic.  ``kv_capacity_ratio``
    is pool MATH, not a device measurement — allocatable blocks at
    equal HBM bytes, fp8 codes + per-row scale overhead vs f32 — so it
    ships real everywhere.  The device-measured fields
    (``kv_decode_drift`` from a serving run under fp8 KV,
    ``quant_train_mfu`` from a quantized training step on TPU) ship
    null unless THIS run filled their telemetry gauges — the
    null-when-unmeasured honesty rule."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.ops.quant_kv import (kv_blocks_in_budget,
                                        resolve_kv_dtype)
    from mxnet_tpu.ops.quant_matmul import resolve_compute_dtype
    # a ~0.5B-class serving geometry; the ratio is budget-invariant
    # past integer rounding
    geom = dict(num_layers=24, num_kv_heads=8, head_dim=128,
                block_size=16)
    budget = 8 << 30
    f32_blocks = kv_blocks_in_budget(budget, **geom)
    fp8_blocks = kv_blocks_in_budget(budget, kv_dtype="fp8", **geom)
    blk = {
        "quant_schema_version": QUANT_SCHEMA_VERSION,
        "compute_dtype": resolve_compute_dtype() or "fp32",
        "kv_dtype": resolve_kv_dtype() or "fp32",
        "kv_capacity_ratio": round(fp8_blocks / f32_blocks, 3),
        "kv_decode_drift": None,
        "quant_train_mfu": None,
    }
    if telemetry.enabled():
        v = telemetry.value("serving.kv_decode_drift")
        if v is not None:
            blk["kv_decode_drift"] = v
        v = telemetry.value("quant.train_mfu")
        if v is not None:
            blk["quant_train_mfu"] = v
    if blk["kv_decode_drift"] is None and blk["quant_train_mfu"] is None:
        blk["note"] = ("drift/MFU unmeasured this run (nulls, not "
                       "zeros); drift evidence: tools/serve_loadgen.py "
                       "--kv-dtype fp8 and tools/tpu_queue_runner.py "
                       "--chaos serving under MXTPU_KV_DTYPE=fp8")
    return blk


_RESNET50_GRAD_BYTES = 25_557_032 * 2   # param count x bf16


def _scaling_projection(resnet_result: dict, rec_result: dict = None) -> dict:
    """ICI+DCN+input-feed roofline from a measured ResNet step (shared by
    the live-TPU and cached-fallback paths so the two can't diverge).

    The 512-chip row exists to exercise the DCN term (two v5e slices);
    the BASELINE metric itself is 8->256, inside one ICI domain.  The
    input-feed cap uses this host's measured decode ceiling scaled to a
    real v5e pod host (ct5lp-hightpu-4t: 112 vCPUs vs this host's
    os.cpu_count()), with the scale disclosed in the inputs block.
    """
    try:
        from tools.scaling_efficiency import project_ici_scaling
        step_ms = resnet_result["batch"] / resnet_result["value"] * 1e3
        kw = {}
        try:
            pipe = (rec_result or {}).get("input_pipeline") or {}
            sweep = pipe.get("decode_thread_sweep") or []
            best = max(r["img_s"] for r in sweep)
            # cores recorded WITH the sweep (bench stores host_cores at
            # measurement time): a cached payload replayed on a different
            # box must scale by the cores that produced the img/s number
            cores = pipe.get("host_cores") or os.cpu_count() or 1
            kw = {"host_decode_imgs_per_sec": best,
                  "per_chip_imgs_per_sec": resnet_result["value"],
                  "host_core_scale": 112.0 / cores}
            # de-rate the pure core ratio by the pool's MEASURED thread
            # scaling: marginal img/s per added thread (slope across the
            # in-core sweep points) over the 1-thread img/s. Sweep points
            # past the core count only measure oversubscription, not
            # parallel efficiency, so they are excluded; with a single
            # in-core point (1-core host) the efficiency is unmeasurable
            # and the projection discloses the linearity assumption.
            rows = sorted({r["threads"]: r["img_s"] for r in sweep}.items())
            in_core = [(t, v) for t, v in rows if t <= cores]
            if len(in_core) >= 2 and rows[0][0] >= 1:
                per_thread_1 = rows[0][1] / rows[0][0]
                (t_lo, v_lo), (t_hi, v_hi) = in_core[0], in_core[-1]
                slope = (v_hi - v_lo) / (t_hi - t_lo)
                kw["host_thread_slope_img_s"] = slope
                kw["host_parallel_efficiency"] = max(
                    0.0, min(1.0, slope / per_thread_1))
        except (ValueError, KeyError, TypeError, AttributeError,
                ZeroDivisionError):
            pass  # no measured sweep in this payload: feed cap unmodeled
        return project_ici_scaling(round(step_ms, 2), _RESNET50_GRAD_BYTES,
                                   chips=(8, 64, 256, 512), **kw)
    except Exception as e:  # noqa: BLE001 — record, never void the bench
        return {"error": f"{type(e).__name__}: {e}"}


def _run_bench() -> dict:
    _enable_compile_cache()
    model = os.environ.get("MXTPU_BENCH_MODEL", "all")
    if os.environ.get("MXTPU_BENCH_CPU_SMOKE", "") == "1":
        # wedged-tunnel fallback: CPU numbers are placeholders (the real
        # evidence is last_known_tpu) — one tiny fp32 synthetic ResNet run
        # keeps total fallback time in single-digit minutes (bf16 is
        # EMULATED on CPU and ~10x slower)
        os.environ["MXTPU_BENCH_DTYPE"] = "fp32"
        os.environ["MXTPU_BENCH_BATCH"] = "4"
        os.environ["MXTPU_BENCH_WARMUP"] = "1"
        result = _bench_resnet(data_mode="synthetic", iters=1,
                               cost_analysis=False)
        result["extra"] = {"note": "cpu smoke mode: bert/rec/bandwidth "
                                   "skipped (see last_known_tpu)"}
        # fallback still carries the round's tunnel-independent evidence:
        # the ICI scaling projection from the cached TPU step time, and
        # the queued on-chip experiment list the verify skill maintains
        cached = _load_tpu_cache()
        if cached:
            result["extra"]["scaling_projection"] = _scaling_projection(
                cached["result"],
                cached["result"].get("extra", {}).get(
                    "resnet_rec_pipeline"))
        result["extra"]["queued_tpu_experiments"] = (
            "tools/tpu_queue_runner.py owns the queue (conv MFU matrix "
            "-> bench refresh -> flash long-seq 2k-32k with naive-OOM "
            "footprint -> bert batch-128), probe-gated with resumable "
            "state in .tpu_queue/state.json; the probe trail in "
            ".tpu_queue/runner.log documents tunnel health over time")
        ml = _load_memlevers()
        if ml is not None:   # measured on-chip lever numbers survive the
            result["extra"]["memory_levers"] = ml   # fallback too
        try:   # attach the probe trail itself as fallback evidence
            qlog = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".tpu_queue", "runner.log")
            with open(qlog) as f:
                tail = f.readlines()[-8:]
            result["extra"]["tunnel_probe_trail"] = [l.strip()
                                                     for l in tail]
        except OSError:
            pass
        return result
    profile = os.environ.get("MXTPU_BENCH_PROFILE", "") == "1"
    if profile:
        from mxnet_tpu import profiler
        profiler.set_config(profile_all=True,
                            filename=os.environ.get(
                                "MXTPU_BENCH_PROFILE_DIR", "bench_profile"))
        profiler.start()
    try:
        if model == "bert":
            return _bench_bert()
        if model in ("resnet50", "resnet"):
            return _bench_resnet()
        # "all": BERT first (own JSON line), ResNet last (headline line the
        # driver parses); BERT summary rides along in "extra"
        bert = None
        try:
            bert = _bench_bert()
            print(json.dumps(bert), flush=True)
        except Exception as e:  # noqa: BLE001 — resnet headline must still run
            bert = {"metric": "bert_base_train_samples_per_sec",
                    "value": 0.0, "unit": "samples/s", "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(bert), flush=True)
        # input pipeline on the clock: short rec-fed run (VERDICT r2 #2)
        rec = None
        try:
            rec = _bench_resnet(data_mode="rec", iters=10,
                                cost_analysis=False)
            print(json.dumps(rec), flush=True)
        except Exception as e:  # noqa: BLE001
            rec = {"metric": "resnet50_rec_pipeline",
                   "error": f"{type(e).__name__}: {e}"}
        try:
            bw = _kvstore_bandwidth()
        except Exception as e:  # noqa: BLE001
            bw = {"error": f"{type(e).__name__}: {e}"}
        result = _bench_resnet(data_mode="synthetic")
        result["extra"] = {"bert": bert, "resnet_rec_pipeline": rec,
                           "kvstore_bandwidth": bw}
        try:
            result["extra"]["tpu_bandwidth"] = _tpu_bandwidth()
        except Exception as e:  # noqa: BLE001
            result["extra"]["tpu_bandwidth"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["llama_decode"] = _bench_decode()
        except Exception as e:  # noqa: BLE001
            result["extra"]["llama_decode"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["serving"] = _bench_serving()
        except Exception as e:  # noqa: BLE001
            result["extra"]["serving"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["elastic"] = _bench_elastic()
        except Exception as e:  # noqa: BLE001
            result["extra"]["elastic"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["fleet"] = _bench_fleet()
        except Exception as e:  # noqa: BLE001
            result["extra"]["fleet"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["lint"] = _bench_lint()
        except Exception as e:  # noqa: BLE001
            result["extra"]["lint"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["multiproc"] = _bench_multiproc()
        except Exception as e:  # noqa: BLE001
            result["extra"]["multiproc"] = {
                "error": f"{type(e).__name__}: {e}"}
        try:
            result["extra"]["quant"] = _bench_quant()
        except Exception as e:  # noqa: BLE001
            result["extra"]["quant"] = {
                "error": f"{type(e).__name__}: {e}"}
        result["extra"]["scaling_projection"] = _scaling_projection(
            result, rec)
        ml = _load_memlevers()
        if ml is not None:
            result["extra"]["memory_levers"] = ml
        return result
    finally:
        if profile:
            from mxnet_tpu import profiler
            profiler.stop()


LINT_SCHEMA_VERSION = 1


def _bench_lint() -> dict:
    """Static-correctness evidence (ISSUE 16): the full mxlint sweep
    (HB01-HB20, including the use-after-donate dataflow pass) over the
    in-tree ``mxnet_tpu`` package, shipped with the bench line so every
    round records that the measured code was donation-clean.
    ``findings`` is a GATE — the tree is kept at zero and a regression
    shows up in the next bench diff; ``suppressions`` counts the
    per-line ``# mxlint: disable=`` opt-outs so silently growing the
    grandfather list is visible too."""
    from mxnet_tpu.lint.api import lint_paths
    from mxnet_tpu.lint.rules import ALL_RULE_IDS
    from mxnet_tpu.lint.suppressions import parse_suppressions
    import mxnet_tpu.lint as _lint
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(
        _lint.__file__)))
    viol, n_files = lint_paths([pkg])
    n_supp = 0
    for root, _dirs, names in os.walk(pkg):
        for n in names:
            if not n.endswith(".py"):
                continue
            try:
                with open(os.path.join(root, n), encoding="utf-8") as f:
                    supp, _unknown = parse_suppressions(f.read())
            except OSError:
                continue
            n_supp += len(supp)
    by_rule = {}
    for v in viol:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    blk = {
        "lint_schema_version": LINT_SCHEMA_VERSION,
        "rules_enabled": len(ALL_RULE_IDS),
        "files_checked": n_files,
        "suppressions": n_supp,
        "findings": len(viol),
        "ok": not viol,
    }
    if by_rule:
        blk["by_rule"] = by_rule
    return blk


def _stamp_parallelism(result: dict, trainer) -> dict:
    """Stamp the mesh shape + `parallelism` block (ISSUE 11) onto a
    bench payload: the mesh is configuration (always stamped);
    pp_bubble_frac is the analytic 1F1B fraction (present only when a
    pipeline axis exists); tp_collective_ms is MEASURED-only and stays
    null until a tp>1 TPU round fills it (PR 6 honesty rule)."""
    try:
        from mxnet_tpu.parallel.mesh import parallelism_block
        from mxnet_tpu.parallel.pipeline_parallel import bubble_fraction
        cfg = trainer.mesh_config
        pp_m = trainer._pp_microbatches if cfg.pp > 1 else None
        pb = bubble_fraction(cfg.pp, pp_m) if cfg.pp > 1 else None
        result["mesh"] = cfg.as_dict()
        result["parallelism"] = parallelism_block(
            cfg, pp_microbatches=pp_m, pp_bubble_frac=pb,
            tp_collective_ms=None)
    except Exception as e:  # noqa: BLE001 — observability never voids
        result["parallelism"] = {"error": f"{type(e).__name__}: {e}"}
    return result


def _stamp_telemetry(result: dict) -> dict:
    """Stamp the payload with the telemetry schema version (ISSUE 9):
    consumers of bench JSON / telemetry snapshots gate field parsing on
    it.  None when mxnet_tpu is not importable (probe-failure paths) —
    null-when-unmeasured, never a guessed constant."""
    try:
        from mxnet_tpu.telemetry import SCHEMA_VERSION
        result["telemetry_schema_version"] = SCHEMA_VERSION
    except Exception:  # noqa: BLE001 — stamping must not void the bench
        result["telemetry_schema_version"] = None
    return result


_TPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".bench_last_tpu.json")
_BENCH_FULL = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".bench_full.json")
_HEADLINE_BUDGET = 1500


def _compact_line(result: dict, budget: int = _HEADLINE_BUDGET) -> str:
    """Serialize the driver-parsed FINAL stdout line, guaranteed small.

    The driver reads only a ~2KB tail window of stdout (round-4 lesson:
    the 1,827-byte r03 line parsed; the ~3.5KB r04 fallback recorded
    `parsed: null`), so the last line must stay under budget no matter
    how much evidence the run produced.  The full payload goes to an
    earlier stdout line and to `.bench_full.json`; this line carries the
    headline metric plus scalar summaries, added in priority order with
    the serialized size re-checked after every addition.
    """
    compact = {k: result[k] for k in
               ("metric", "value", "unit", "vs_baseline") if k in result}
    extra = result.get("extra") or {}
    cands = []
    for k in ("platform", "mfu", "mfu_live", "tflops_delivered", "batch",
              "dtype", "data", "s2d_stem", "flops_source",
              "steps_per_call", "dispatch_ms_per_step",
              "platform_requested", "platform_actual",
              "telemetry_schema_version"):
        if k in result and result[k] is not None:
            cands.append((k, result[k]))
    par = result.get("parallelism") or {}
    if par.get("mesh_spec"):
        cands.append(("mesh", par["mesh_spec"]))
    if "error" in result:
        err = str(result["error"])
        cands.append(("error",
                      err if len(err) <= 160 else err[:157] + "..."))
    comm = result.get("comm") or {}
    if comm.get("zero1"):
        # sharded-sync evidence (zeros-only CPU blocks stay out of the
        # budget; the full block always lands in .bench_full.json)
        for name, key in (("comm_ms", "collective_ms"),
                          ("comm_gb_s", "est_ici_gb_s"),
                          ("comm_wire", "wire_dtype"),
                          ("comm_exposed_ms", "exposed_comm_ms"),
                          ("comm_overlap_frac", "overlap_frac"),
                          ("comm_mb_reduced", None)):
            v = (round(comm.get("bytes_reduced_per_step", 0) / 1e6, 1)
                 if key is None else comm.get(key))
            if v is not None:
                cands.append((name, v))

    def _num(d, *path):
        for p in path:
            if not isinstance(d, dict):
                return None
            d = d.get(p)
        ok = isinstance(d, (int, float)) and not isinstance(d, bool)
        return d if ok else None

    named = (
        ("bert_samples_s", ("bert", "value")),
        ("bert_mfu", ("bert", "mfu")),
        ("rec_img_s", ("resnet_rec_pipeline", "value")),
        ("rec_overlap_eff", ("resnet_rec_pipeline", "input_pipeline",
                             "overlap_efficiency")),
        ("rec_img_s_overlap", ("resnet_rec_pipeline", "input_pipeline",
                               "img_s_overlapped")),
        ("decode_tok_s", ("llama_decode", "tokens_per_sec")),
        ("serve_tok_s", ("serving", "tokens_s_chip")),
        ("serve_p99_ms", ("serving", "p99_ms")),
        ("serve_occupancy", ("serving", "occupancy")),
        ("serve_prefix_hit", ("serving", "prefix_hit_rate")),
        ("router_p99_ms", ("serving", "router_p99_ms")),
        ("serve_handoff_ms", ("serving", "handoff_ms")),
        ("serve_prefill_occ", ("serving", "prefill_pool_occupancy")),
        ("serve_decode_occ", ("serving", "decode_pool_occupancy")),
        ("elastic_reshard_ms", ("elastic", "reshard_ms")),
        ("elastic_pause_ms", ("elastic", "pause_ms")),
        ("elastic_epoch", ("elastic", "membership_epoch")),
        ("fleet_slowest_rank", ("fleet", "slowest_rank")),
        ("fleet_skew", ("fleet", "step_ms_skew")),
        ("fleet_scrape_ms", ("fleet", "scrape_ms")),
        ("tpu_h2d_gb_s", ("tpu_bandwidth", "h2d_gb_s")),
        ("tpu_hbm_gb_s", ("tpu_bandwidth", "hbm_copy_gb_s")),
        ("kv_per_key_speedup", ("kvstore_bandwidth", "per_key_speedup")),
    )
    for name, path in named:
        v = _num(extra, *path)
        if v is not None:
            cands.append((name, v))
    proj = extra.get("scaling_projection")
    if isinstance(proj, dict):
        for row in proj.get("projection", []):
            if isinstance(row, dict) and row.get("chips") in (8, 256):
                v = row.get("projected_efficiency")
                if v is not None:
                    cands.append((f"proj_eff_{row['chips']}", v))
    lk = result.get("last_known_tpu")
    if isinstance(lk, dict):
        lkr = lk.get("result") or {}
        lkc = {"cached_at": lk.get("cached_at")}
        for k in ("value", "mfu", "batch", "dtype"):
            if k in lkr:
                lkc[k] = lkr[k]
        v = _num(lkr.get("extra") or {}, "bert", "value")
        if v is not None:
            lkc["bert_samples_s"] = v
        cands.append(("last_known_tpu", lkc))
    # generic sweep: future extras (memory-lever measurements, new
    # sweeps) surface automatically as long as they are scalars, one or
    # two levels deep, and the budget still allows them
    handled = {"bert", "resnet_rec_pipeline", "llama_decode", "serving",
               "elastic", "fleet", "tpu_bandwidth", "kvstore_bandwidth",
               "scaling_projection"}
    for k in sorted(extra):
        if k in handled:
            continue
        v = extra[k]
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            cands.append((k, v))
        elif isinstance(v, dict):
            for k2 in sorted(v):
                v2 = v[k2]
                if isinstance(v2, (int, float)) and \
                        not isinstance(v2, bool):
                    cands.append((f"{k}.{k2}", v2))
    for k, v in cands:
        trial = dict(compact)
        trial[k] = v
        if len(json.dumps(trial)) <= budget:
            compact = trial
    return json.dumps(compact)
_KNOBS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      ".bench_knobs.json")


def _apply_knobs_file() -> None:
    """Fill unset bench knobs from the measured conv-matrix winner
    (written by tools/tpu_queue_runner.py after tpu_conv_experiments.py).
    Env always wins; this only makes the driver's plain `python bench.py`
    run the best measured config by default."""
    try:
        with open(_KNOBS) as f:
            k = json.load(f)
    except (OSError, ValueError):
        return
    for env_name, key in (("MXTPU_RESNET_S2D", "resnet_s2d"),
                          ("MXTPU_CONV_LAYOUT", "conv_layout"),
                          ("MXTPU_BENCH_BATCH", "batch"),
                          ("MXTPU_FLASH_BQ", "flash_bq"),
                          ("MXTPU_FLASH_BK", "flash_bk")):
        v = k.get(key)
        if v is not None and env_name not in os.environ:
            os.environ[env_name] = str(v)


def _save_tpu_cache(result: dict) -> None:
    try:
        with open(_TPU_CACHE, "w") as f:
            json.dump({"cached_at": time.strftime("%Y-%m-%d %H:%M:%S"),
                       "result": result}, f)
    except OSError:
        pass


_MEMLEVERS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".bench_memlevers.json")


def _load_memlevers() -> dict | None:
    """Measured memory-lever summary written by the queue runner
    (tools/memory_levers.py summarize); committed evidence like
    .bench_knobs.json."""
    try:
        with open(_MEMLEVERS) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_tpu_cache() -> dict | None:
    try:
        with open(_TPU_CACHE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def main() -> int:
    _apply_knobs_file()
    # Probe with capped retries + exponential backoff (~6.5 min worst
    # case at the default 3x120s, seconds when healthy).  The old
    # 6x120s+45s schedule burned ~12-16 min of the round on a wedged
    # tunnel (r04/r05) for no extra signal: a tunnel that ignores three
    # spaced probes ignores six.  MXTPU_PROBE_RETRIES raises the cap
    # when a round wants to wait out a flaky tunnel.
    attempts = int(os.environ.get(
        "MXTPU_PROBE_RETRIES",
        os.environ.get("MXTPU_BENCH_PROBE_ATTEMPTS", "3")))
    timeout = float(os.environ.get("MXTPU_BENCH_PROBE_TIMEOUT", "120"))
    backoff = float(os.environ.get("MXTPU_PROBE_BACKOFF", "5"))
    error = None

    platform = None
    fell_back = False
    requested = "cpu" if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
        else "tpu"
    if requested == "cpu":
        # explicitly CPU-pinned: nothing to probe, but still strip the axon
        # plugin — a wedged tunnel can hang backend discovery even when the
        # requested platform is cpu (same defense as tests/conftest.py)
        platform = "cpu"
        _force_cpu()
    else:
        for i in range(attempts):
            platform = _probe_backend(timeout)
            if platform is not None:
                break
            if i < attempts - 1:
                time.sleep(min(backoff * 2 ** i, 60.0))
    if os.environ.get("MXTPU_BENCH_REQUIRE_TPU", "") == "1" and \
            platform != "tpu":
        # fail-FAST, fail-LOUD (ISSUE 6 honesty fix): rounds 4-5 fell
        # back to CPU silently enough that CPU zeros were read as
        # measurements.  With the flag set, a non-TPU backend is an
        # ERROR exit — no fallback numbers to misread.
        result = {"metric": "resnet50_train_images_per_sec", "value": 0.0,
                  "unit": "img/s", "vs_baseline": 0.0,
                  "platform_requested": "tpu",
                  "platform_actual": platform or "none",
                  "error": ("MXTPU_BENCH_REQUIRE_TPU=1: backend is "
                            f"{platform or 'unreachable'} after "
                            f"{attempts} probes; refusing CPU fallback")}
        _stamp_telemetry(result)
        print(json.dumps(result), flush=True)
        if os.environ.get("MXTPU_BENCH_NO_COMPACT", "") != "1":
            print(_compact_line(result), flush=True)
        return 2
    if platform is None:
        error = (f"backend probe failed after {attempts} attempts "
                 f"({timeout:.0f}s timeout each); falling back to CPU")
        fell_back = True
        os.environ["MXTPU_BENCH_CPU_SMOKE"] = "1"
        _force_cpu()

    try:
        result = _run_bench()
    except Exception as e:  # noqa: BLE001 — always emit the JSON line
        err = f"bench failed on {platform}: {type(e).__name__}: {e}"
        error = err if error is None else f"{error}; then {err}"
        result = None
        if platform != "cpu":
            # accelerator bench died mid-run: a fresh CPU subprocess still
            # gets the driver a parseable number (in-process backend switch
            # is impossible once jax initialized the accelerator)
            result = _cpu_fallback_subprocess()
            if result is not None:
                fell_back = True
        if result is None:
            result = {"metric": "resnet50_train_images_per_sec",
                      "value": 0.0, "unit": "img/s", "vs_baseline": 0.0}
    # requested-vs-actual stamps (ISSUE 6 honesty fix): the JSON carries
    # what the round ASKED for and what it GOT, so a CPU fallback can
    # never masquerade as an accelerator measurement in post-processing
    result["platform_requested"] = requested
    result["platform_actual"] = "cpu" if fell_back else \
        (result.get("platform") or platform or "cpu")
    if fell_back:
        # LOUD marker: this number is NOT an accelerator number (r2 weak #8)
        result["platform"] = "cpu-FALLBACK"
        # a wedged tunnel must not erase real measurements: attach the
        # most recent successful TPU run (timestamped) for the record
        cached = _load_tpu_cache()
        if cached is not None:
            result["last_known_tpu"] = cached
    elif (result.get("platform") == "tpu"
          and os.environ.get("MXTPU_BENCH_MODEL", "all") == "all"):
        # single-model probe runs (e.g. a bert batch sweep) must not
        # replace the full-payload cache the fallback path relies on
        _save_tpu_cache(result)
    if error is not None:
        result["error"] = error
    _stamp_telemetry(result)
    # Full payload: artifact file + an EARLIER stdout line (the driver's
    # ~2KB tail window must only ever contain the compact headline below)
    try:
        with open(_BENCH_FULL, "w") as f:
            json.dump(result, f, indent=1)
    except OSError:
        pass
    print(json.dumps(result), flush=True)
    if os.environ.get("MXTPU_BENCH_NO_COMPACT", "") != "1":
        print(_compact_line(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
