"""Headline benchmark: ResNet-50 fused training step, images/sec.

Mirrors the reference's headline number (BASELINE.md: ResNet-50 v1 training
throughput, ~380 img/s/GPU fp32 on V100 from docs/faq/perf.md). Here the
whole record->forward->backward->update loop is ONE jitted XLA program
(SURVEY.md §3.2 TPU mapping) on whatever accelerator jax exposes.

Prints ONE JSON line:
  {"metric": "resnet50_train_images_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": N/380}
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 380.0  # ResNet-50 v1 fp32 per-V100 (BASELINE.md)


def main():
    batch = int(os.environ.get("MXTPU_BENCH_BATCH", "128"))
    iters = int(os.environ.get("MXTPU_BENCH_ITERS", "20"))
    warmup = int(os.environ.get("MXTPU_BENCH_WARMUP", "3"))
    dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bf16")

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # CPU smoke config so the bench is runnable anywhere
        batch = min(batch, 16)
        iters = min(iters, 5)

    if dtype == "bf16":
        # MXU-native mixed precision: conv/matmul inputs cast to bfloat16,
        # softmax/norms in fp32 (mx.amp op lists); compiled into the step
        from mxnet_tpu import amp
        amp.init(target_dtype="bfloat16")

    net = resnet50_v1()
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = DataParallelTrainer(net, loss_fn, "sgd",
                                  {"learning_rate": 0.1, "momentum": 0.9},
                                  mesh=mesh)

    data = mx.nd.random.uniform(shape=(batch, 3, 224, 224))
    label = mx.nd.zeros((batch,))

    for _ in range(max(warmup, 1)):
        loss = trainer.step(data, label)
    loss.asnumpy()

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = trainer.step(data, label)
    loss.asnumpy()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
