"""Build the native host runtime (src/ -> mxnet_tpu/utils/libmxtpu.so).

Usage: python setup_native.py build
Requires cmake + a C++17 compiler + libjpeg headers (all in the standard
image). The library is optional: every consumer falls back to pure Python
when it is absent (mxnet_tpu/utils/native.py:available()).
"""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.abspath(__file__))


def build():
    build_dir = os.path.join(ROOT, "src", "build")
    gen = []
    try:
        subprocess.run(["ninja", "--version"], capture_output=True, check=True)
        gen = ["-G", "Ninja"]
    except Exception:
        pass
    subprocess.check_call(
        ["cmake", "-S", os.path.join(ROOT, "src"), "-B", build_dir] + gen)
    subprocess.check_call(["cmake", "--build", build_dir])
    print("built:", os.path.join(ROOT, "mxnet_tpu", "utils", "libmxtpu.so"))


if __name__ == "__main__":
    if len(sys.argv) < 2 or sys.argv[1] != "build":
        print(__doc__)
        sys.exit(1)
    build()
