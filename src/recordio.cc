/*
 * recordio.cc — mmap-backed RecordIO reader + buffered writer.
 *
 * Framing (compatible with dmlc-core recordio, see reference
 * dmlc-core/src/recordio.cc behavior): each part is
 *   uint32 magic (0xced7230a) | uint32 lrec | payload | pad to 4B
 * where lrec = (cflag << 29) | length. cflag: 0 = whole record,
 * 1 = first part, 2 = middle part, 3 = last part.
 */
#include "mxtpu.h"

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

thread_local std::string g_last_error;

inline uint32_t ReadU32(const uint8_t *p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

struct RecordRef {
  uint64_t offset;   // offset of first payload byte
  uint32_t length;   // payload length of this part
  bool multipart;    // cflag != 0 at this position
};

struct Reader {
  int fd = -1;
  const uint8_t *base = nullptr;
  size_t size = 0;
  std::vector<RecordRef> index;   // one entry per logical record
  std::string scratch;            // assembly buffer for multipart reads
};

}  // namespace

extern "C" {

const char *mxtpu_last_error(void) { return g_last_error.c_str(); }

void *mxtpu_recordio_open(const char *path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) {
    g_last_error = std::string("open failed: ") + path;
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    g_last_error = "fstat failed";
    return nullptr;
  }
  auto *r = new Reader();
  r->fd = fd;
  r->size = static_cast<size_t>(st.st_size);
  if (r->size > 0) {
    void *m = mmap(nullptr, r->size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      ::close(fd);
      delete r;
      g_last_error = "mmap failed";
      return nullptr;
    }
    r->base = static_cast<const uint8_t *>(m);
    madvise(const_cast<uint8_t *>(r->base), r->size, MADV_WILLNEED);
  }
  // Single sequential scan to index logical record boundaries.
  size_t pos = 0;
  while (pos + 8 <= r->size) {
    if (ReadU32(r->base + pos) != kMagic) {
      g_last_error = "magic mismatch at offset " + std::to_string(pos);
      munmap(const_cast<uint8_t *>(r->base), r->size);
      ::close(fd);
      delete r;
      return nullptr;
    }
    uint32_t lrec = ReadU32(r->base + pos + 4);
    uint32_t cflag = lrec >> 29u;
    uint32_t length = lrec & ((1u << 29u) - 1u);
    if (pos + 8 + length > r->size) {
      g_last_error = "truncated record at offset " + std::to_string(pos);
      munmap(const_cast<uint8_t *>(r->base), r->size);
      ::close(fd);
      delete r;
      return nullptr;
    }
    if (cflag == 0 || cflag == 1) {
      r->index.push_back({pos + 8, length, cflag != 0});
    }
    pos += 8 + ((length + 3u) & ~3u);
  }
  return r;
}

int64_t mxtpu_recordio_count(void *handle) {
  if (!handle) return -1;
  return static_cast<int64_t>(static_cast<Reader *>(handle)->index.size());
}

int64_t mxtpu_recordio_read(void *handle, int64_t i, void **out) {
  auto *r = static_cast<Reader *>(handle);
  if (!r || i < 0 || i >= static_cast<int64_t>(r->index.size())) {
    g_last_error = "index out of range";
    return -1;
  }
  const RecordRef &ref = r->index[static_cast<size_t>(i)];
  if (!ref.multipart) {
    *out = const_cast<uint8_t *>(r->base + ref.offset);
    return ref.length;
  }
  // Assemble continuation parts into the scratch buffer.
  r->scratch.assign(reinterpret_cast<const char *>(r->base + ref.offset),
                    ref.length);
  size_t pos = ref.offset + ((ref.length + 3u) & ~3u);
  while (pos + 8 <= r->size) {
    uint32_t lrec = ReadU32(r->base + pos + 4);
    uint32_t cflag = lrec >> 29u;
    uint32_t length = lrec & ((1u << 29u) - 1u);
    r->scratch.append(reinterpret_cast<const char *>(r->base + pos + 8),
                      length);
    pos += 8 + ((length + 3u) & ~3u);
    if (cflag == 3) break;
  }
  *out = const_cast<char *>(r->scratch.data());
  return static_cast<int64_t>(r->scratch.size());
}

void mxtpu_recordio_close(void *handle) {
  auto *r = static_cast<Reader *>(handle);
  if (!r) return;
  if (r->base) munmap(const_cast<uint8_t *>(r->base), r->size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

/* ---------------- writer ---------------- */

namespace {
struct Writer {
  FILE *f = nullptr;
  int64_t pos = 0;
};
}  // namespace

void *mxtpu_recordio_writer_open(const char *path) {
  FILE *f = std::fopen(path, "wb");
  if (!f) {
    g_last_error = std::string("fopen failed: ") + path;
    return nullptr;
  }
  auto *w = new Writer();
  w->f = f;
  return w;
}

int64_t mxtpu_recordio_writer_write(void *handle, const void *buf,
                                    int64_t size) {
  auto *w = static_cast<Writer *>(handle);
  if (!w || size < 0 || size >= (1ll << 29)) {
    g_last_error = "bad write (record too large for single part?)";
    return -1;
  }
  int64_t start = w->pos;
  uint32_t header[2] = {kMagic, static_cast<uint32_t>(size)};
  uint32_t pad = (4u - static_cast<uint32_t>(size % 4)) % 4u;
  static const char zeros[4] = {0, 0, 0, 0};
  if (std::fwrite(header, 4, 2, w->f) != 2 ||
      std::fwrite(buf, 1, static_cast<size_t>(size), w->f) !=
          static_cast<size_t>(size) ||
      (pad && std::fwrite(zeros, 1, pad, w->f) != pad)) {
    g_last_error = "record write failed (disk full?)";
    return -1;
  }
  w->pos += 8 + size + pad;
  return start;
}

int mxtpu_recordio_writer_close(void *handle) {
  auto *w = static_cast<Writer *>(handle);
  if (!w) return 0;
  int rc = 0;
  if (w->f && std::fclose(w->f) != 0) {
    g_last_error = "fclose failed (data may be truncated)";
    rc = -1;
  }
  delete w;
  return rc;
}

}  // extern "C"
