/*
 * mxtpu.h — C ABI of the native host runtime.
 *
 * TPU-native replacement for the reference's host-side IO stack
 * (src/io/iter_image_recordio_2.cc, iter_prefetcher.h, iter_batchloader.h
 * and dmlc-core/src/recordio). The XLA runtime owns the device; this
 * library owns the host work that feeds it: RecordIO scanning/reading,
 * JPEG decode, and a prefetching batch-assembly thread pool.
 *
 * All functions are exported with C linkage for ctypes consumption from
 * mxnet_tpu/utils/native.py. Error convention: pointer-returning calls
 * return NULL on failure, count/size-returning calls return a negative
 * value; mxtpu_last_error() gives a human-readable message.
 */
#ifndef MXTPU_H_
#define MXTPU_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ------------------------------------------------------------------ */
/* Error handling                                                      */
const char *mxtpu_last_error(void);

/* ------------------------------------------------------------------ */
/* RecordIO reader: mmap the .rec file, scan magic+lrec framing once   */
/* at open to build an in-memory index, then O(1) random reads with    */
/* zero-copy for single-part records.                                  */
void *mxtpu_recordio_open(const char *path);
int64_t mxtpu_recordio_count(void *handle);
/* Returns payload size and sets *out to a pointer valid until the next
 * read on the same handle (multi-part records are assembled into a
 * per-handle scratch buffer; single-part records point into the mmap). */
int64_t mxtpu_recordio_read(void *handle, int64_t i, void **out);
void mxtpu_recordio_close(void *handle);

/* RecordIO writer (framing identical to dmlc-core recordio). */
void *mxtpu_recordio_writer_open(const char *path);
/* Returns byte offset of the record start, or -1. */
int64_t mxtpu_recordio_writer_write(void *handle, const void *buf,
                                    int64_t size);
/* Returns 0 on success, -1 if the final flush failed. */
int mxtpu_recordio_writer_close(void *handle);

/* ------------------------------------------------------------------ */
/* JPEG decode via libjpeg: RGB uint8 HWC output.                      */
/* Returns 0 on success; fills width/height/channels. If out is NULL   */
/* only the header is parsed (use to size the buffer: h*w*3).          */
int mxtpu_jpeg_decode(const void *jpeg, int64_t size, uint8_t *out,
                      int64_t out_capacity, int32_t *height,
                      int32_t *width, int32_t *channels);

/* ------------------------------------------------------------------ */
/* Prefetching batch loader: worker threads pull record indices from   */
/* a schedule, read (and optionally JPEG-decode + resize) them, and    */
/* push assembled batches into a bounded queue — the role of           */
/* PrefetcherIter + BatchLoader in the reference.                      */
/*                                                                     */
/* mode 0: raw bytes — batch is records concatenated, with per-record  */
/*         int64 offsets (n+1 entries).                                */
/* mode 1: image — each record is IRHeader(+label)+JPEG; batch is      */
/*         uint8 NHWC data (center-cropped/resized to edge x edge)     */
/*         plus float32 labels.                                        */
void *mxtpu_prefetch_create(const char *rec_path, const int64_t *indices,
                            int64_t n_indices, int64_t batch_size,
                            int32_t n_threads, int32_t queue_depth,
                            int32_t mode, int32_t edge, int32_t label_width);
/* Blocks until the next batch is ready. Returns number of records in
 * the batch (< batch_size only for the last partial batch; 0 at end of
 * epoch, -1 on error). The returned pointers are valid until the next
 * call to mxtpu_prefetch_next on the same handle.
 * mode 0: *data = concatenated bytes, *aux = int64 offsets[n+1].
 * mode 1: *data = uint8 NHWC batch,   *aux = float32 labels[n*label_width]. */
int64_t mxtpu_prefetch_next(void *handle, void **data, int64_t *data_size,
                            void **aux);
/* Restart the epoch without reopening/re-scanning the .rec file. Pass a
 * new schedule (e.g. reshuffled indices), or indices=NULL to replay the
 * current one. */
void mxtpu_prefetch_reset(void *handle, const int64_t *indices,
                          int64_t n_indices);
/* Error message from the last failed mxtpu_prefetch_next on this handle. */
const char *mxtpu_prefetch_error(void *handle);
void mxtpu_prefetch_free(void *handle);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_H_ */
