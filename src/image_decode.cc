/*
 * image_decode.cc — JPEG decode + bilinear resize on the host CPU.
 *
 * Role of the reference's src/io/image_aug_default.cc decode path
 * (libjpeg-turbo/OpenCV there). Output is RGB uint8 HWC; resize is a
 * separable bilinear to a square `edge` (the classic short-side-resize
 * + center-crop is done by the prefetcher on top of this).
 */
#include "mxtpu.h"

#include <csetjmp>
#include <cstdio>
#include <cstring>
#include <vector>

#include <jpeglib.h>

namespace {

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void JpegErrorExit(j_common_ptr cinfo) {
  auto *err = reinterpret_cast<JpegErrorMgr *>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

}  // namespace

namespace {

/* setjmp/longjmp frame: only POD locals live here; the scratch row buffer
 * is owned by the caller so its destructor runs even on a longjmp'd error
 * return. */
int DecodeImpl(const void *jpeg, int64_t size, uint8_t *out,
               int64_t out_capacity, int32_t *height, int32_t *width,
               int32_t *channels, std::vector<uint8_t> *row) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JpegErrorExit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, static_cast<const unsigned char *>(jpeg),
               static_cast<unsigned long>(size));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  *height = static_cast<int32_t>(cinfo.image_height);
  *width = static_cast<int32_t>(cinfo.image_width);
  *channels = 3;
  if (out == nullptr) {  // header-only probe
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  int64_t needed =
      static_cast<int64_t>(cinfo.image_height) * cinfo.image_width * 3;
  if (out_capacity < needed) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_start_decompress(&cinfo);
  row->resize(static_cast<size_t>(cinfo.output_width) *
              cinfo.output_components);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t *rowptr = out + static_cast<int64_t>(cinfo.output_scanline) *
                                cinfo.output_width * 3;
    if (cinfo.output_components == 3) {
      JSAMPROW rows[1] = {rowptr};
      jpeg_read_scanlines(&cinfo, rows, 1);
    } else {  // grayscale: expand to RGB
      JSAMPROW rows[1] = {row->data()};
      jpeg_read_scanlines(&cinfo, rows, 1);
      for (unsigned x = 0; x < cinfo.output_width; ++x) {
        rowptr[3 * x] = rowptr[3 * x + 1] = rowptr[3 * x + 2] = (*row)[x];
      }
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // namespace

extern "C" {

int mxtpu_jpeg_decode(const void *jpeg, int64_t size, uint8_t *out,
                      int64_t out_capacity, int32_t *height, int32_t *width,
                      int32_t *channels) {
  std::vector<uint8_t> row;
  return DecodeImpl(jpeg, size, out, out_capacity, height, width, channels,
                    &row);
}

}  // extern "C"

/* Shared by prefetch.cc — not part of the C ABI. */
void mxtpu_bilinear_resize_rgb(const uint8_t *src, int sh, int sw,
                               uint8_t *dst, int dh, int dw) {
  const float scale_y = static_cast<float>(sh) / dh;
  const float scale_x = static_cast<float>(sw) / dw;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * scale_y - 0.5f;
    int y0 = fy < 0 ? 0 : static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * scale_x - 0.5f;
      int x0 = fx < 0 ? 0 : static_cast<int>(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(y0 * sw + x0) * 3 + c];
        float v01 = src[(y0 * sw + x1) * 3 + c];
        float v10 = src[(y1 * sw + x0) * 3 + c];
        float v11 = src[(y1 * sw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * 3 + c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}
