/*
 * prefetch.cc — multi-threaded prefetching batch loader.
 *
 * The native equivalent of the reference's PrefetcherIter +
 * BatchLoader + ImageRecordIOParser2 pipeline (src/io/iter_prefetcher.h,
 * iter_batchloader.h, iter_image_recordio_2.cc): worker threads claim
 * whole batches, read records from the mmap'd RecordIO file, optionally
 * JPEG-decode + resize them, and publish completed batches into a
 * bounded, order-preserving queue the Python thread consumes.
 */
#include "mxtpu.h"

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

void mxtpu_bilinear_resize_rgb(const uint8_t *src, int sh, int sw,
                               uint8_t *dst, int dh, int dw);

namespace {

struct Batch {
  std::vector<uint8_t> data;
  std::vector<uint8_t> aux;   // int64 offsets (mode 0) or float labels (mode 1)
  int64_t n_records = 0;
};

struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

struct Prefetcher {
  void *reader = nullptr;
  std::vector<int64_t> indices;
  int64_t batch_size = 0;
  int32_t mode = 0;
  int32_t edge = 0;
  int32_t label_width = 1;
  int64_t n_batches = 0;

  std::vector<std::thread> workers;
  std::atomic<int64_t> next_claim{0};
  int64_t next_deliver = 0;

  std::mutex mu;
  std::condition_variable cv_produce;  // workers wait: queue has room
  std::condition_variable cv_consume;  // consumer waits: next batch ready
  std::map<int64_t, std::unique_ptr<Batch>> ready;
  size_t queue_depth = 4;
  bool stop = false;
  bool failed = false;
  std::string error;

  std::unique_ptr<Batch> current;  // batch handed to Python, kept alive
  std::mutex read_mu;              // RecordIO scratch buffer is per-handle
  int n_threads = 4;
};

void BuildBatch(Prefetcher *p, int64_t b, Batch *out) {
  int64_t start = b * p->batch_size;
  int64_t end = std::min<int64_t>(start + p->batch_size,
                                  static_cast<int64_t>(p->indices.size()));
  int64_t n = end - start;
  out->n_records = n;
  if (p->mode == 0) {
    std::vector<int64_t> offsets(static_cast<size_t>(n) + 1, 0);
    for (int64_t i = 0; i < n; ++i) {
      void *ptr = nullptr;
      int64_t sz;
      {
        std::lock_guard<std::mutex> lk(p->read_mu);
        sz = mxtpu_recordio_read(p->reader, p->indices[start + i], &ptr);
        if (sz < 0) throw std::runtime_error("record read failed");
        out->data.insert(out->data.end(), static_cast<uint8_t *>(ptr),
                         static_cast<uint8_t *>(ptr) + sz);
      }
      offsets[i + 1] = offsets[i] + sz;
    }
    out->aux.resize(offsets.size() * sizeof(int64_t));
    std::memcpy(out->aux.data(), offsets.data(), out->aux.size());
    return;
  }
  // mode 1: image batch, NHWC uint8 + float32 labels
  const int e = p->edge;
  out->data.assign(static_cast<size_t>(n) * e * e * 3, 0);
  std::vector<float> labels(static_cast<size_t>(n) * p->label_width, 0.f);
  std::vector<uint8_t> record, decoded, resized;
  for (int64_t i = 0; i < n; ++i) {
    {
      std::lock_guard<std::mutex> lk(p->read_mu);
      void *ptr = nullptr;
      int64_t sz = mxtpu_recordio_read(p->reader, p->indices[start + i], &ptr);
      if (sz < 0) throw std::runtime_error("record read failed");
      record.assign(static_cast<uint8_t *>(ptr),
                    static_cast<uint8_t *>(ptr) + sz);
    }
    if (record.size() < sizeof(IRHeader))
      throw std::runtime_error("record too small for IRHeader");
    IRHeader hdr;
    std::memcpy(&hdr, record.data(), sizeof(IRHeader));
    const uint8_t *payload = record.data() + sizeof(IRHeader);
    size_t payload_size = record.size() - sizeof(IRHeader);
    if (hdr.flag > 0) {  // label array follows the header
      size_t lab_bytes = static_cast<size_t>(hdr.flag) * 4;
      if (payload_size < lab_bytes)
        throw std::runtime_error("label array exceeds record");
      int nl = std::min<int>(p->label_width, static_cast<int>(hdr.flag));
      std::memcpy(&labels[i * p->label_width], payload, nl * 4);
      payload += lab_bytes;
      payload_size -= lab_bytes;
    } else {
      labels[i * p->label_width] = hdr.label;
    }
    int32_t h, w, c;
    if (mxtpu_jpeg_decode(payload, static_cast<int64_t>(payload_size),
                          nullptr, 0, &h, &w, &c) != 0)
      throw std::runtime_error("jpeg header parse failed");
    decoded.resize(static_cast<size_t>(h) * w * 3);
    if (mxtpu_jpeg_decode(payload, static_cast<int64_t>(payload_size),
                          decoded.data(),
                          static_cast<int64_t>(decoded.size()), &h, &w,
                          &c) != 0)
      throw std::runtime_error("jpeg decode failed");
    // Short-side resize then center crop to edge x edge.
    int rh, rw;
    if (h < w) {
      rh = e;
      rw = static_cast<int>(static_cast<int64_t>(w) * e / h);
    } else {
      rw = e;
      rh = static_cast<int>(static_cast<int64_t>(h) * e / w);
    }
    resized.resize(static_cast<size_t>(rh) * rw * 3);
    mxtpu_bilinear_resize_rgb(decoded.data(), h, w, resized.data(), rh, rw);
    int y0 = (rh - e) / 2, x0 = (rw - e) / 2;
    uint8_t *dst = out->data.data() + static_cast<size_t>(i) * e * e * 3;
    for (int y = 0; y < e; ++y)
      std::memcpy(dst + static_cast<size_t>(y) * e * 3,
                  resized.data() + (static_cast<size_t>(y0 + y) * rw + x0) * 3,
                  static_cast<size_t>(e) * 3);
  }
  out->aux.resize(labels.size() * sizeof(float));
  std::memcpy(out->aux.data(), labels.data(), out->aux.size());
}

void StopWorkers(Prefetcher *p) {
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_produce.notify_all();
  }
  for (auto &t : p->workers) t.join();
  p->workers.clear();
}

void WorkerLoop(Prefetcher *p) {
  for (;;) {
    int64_t b = p->next_claim.fetch_add(1);
    if (b >= p->n_batches) return;
    auto batch = std::make_unique<Batch>();
    try {
      BuildBatch(p, b, batch.get());
    } catch (const std::exception &ex) {
      std::lock_guard<std::mutex> lk(p->mu);
      p->failed = true;
      p->error = ex.what();
      p->cv_consume.notify_all();
      return;
    }
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_produce.wait(lk, [&] {
      return p->stop || p->ready.size() < p->queue_depth ||
             b < p->next_deliver + static_cast<int64_t>(p->queue_depth);
    });
    if (p->stop) return;
    p->ready.emplace(b, std::move(batch));
    p->cv_consume.notify_all();
  }
}

}  // namespace

extern "C" {

void *mxtpu_prefetch_create(const char *rec_path, const int64_t *indices,
                            int64_t n_indices, int64_t batch_size,
                            int32_t n_threads, int32_t queue_depth,
                            int32_t mode, int32_t edge, int32_t label_width) {
  if (batch_size <= 0 || n_indices < 0 || (mode == 1 && edge <= 0))
    return nullptr;
  void *reader = mxtpu_recordio_open(rec_path);
  if (!reader) return nullptr;
  auto *p = new Prefetcher();
  p->reader = reader;
  p->indices.assign(indices, indices + n_indices);
  p->batch_size = batch_size;
  p->mode = mode;
  p->edge = edge;
  p->label_width = label_width > 0 ? label_width : 1;
  p->n_batches = (n_indices + batch_size - 1) / batch_size;
  p->queue_depth = queue_depth > 0 ? static_cast<size_t>(queue_depth) : 4;
  p->n_threads = n_threads > 0 ? n_threads : 4;
  for (int t = 0; t < p->n_threads; ++t)
    p->workers.emplace_back(WorkerLoop, p);
  return p;
}

int64_t mxtpu_prefetch_next(void *handle, void **data, int64_t *data_size,
                            void **aux) {
  auto *p = static_cast<Prefetcher *>(handle);
  if (!p) return -1;
  if (p->next_deliver >= p->n_batches) return 0;  // end of epoch
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_consume.wait(lk, [&] {
    return p->failed || p->ready.count(p->next_deliver) > 0;
  });
  if (p->failed) return -1;  // message available via mxtpu_prefetch_error
  p->current = std::move(p->ready[p->next_deliver]);
  p->ready.erase(p->next_deliver);
  ++p->next_deliver;
  p->cv_produce.notify_all();
  *data = p->current->data.data();
  *data_size = static_cast<int64_t>(p->current->data.size());
  *aux = p->current->aux.data();
  return p->current->n_records;
}

void mxtpu_prefetch_reset(void *handle, const int64_t *indices,
                          int64_t n_indices) {
  auto *p = static_cast<Prefetcher *>(handle);
  if (!p) return;
  StopWorkers(p);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (indices != nullptr) {
      p->indices.assign(indices, indices + n_indices);
      p->n_batches = (n_indices + p->batch_size - 1) / p->batch_size;
    }
    p->ready.clear();
    p->next_claim = 0;
    p->next_deliver = 0;
    p->stop = false;
    p->failed = false;
    p->error.clear();
  }
  for (int t = 0; t < p->n_threads; ++t)
    p->workers.emplace_back(WorkerLoop, p);
}

const char *mxtpu_prefetch_error(void *handle) {
  auto *p = static_cast<Prefetcher *>(handle);
  if (!p) return "";
  std::lock_guard<std::mutex> lk(p->mu);
  return p->error.c_str();
}

void mxtpu_prefetch_free(void *handle) {
  auto *p = static_cast<Prefetcher *>(handle);
  if (!p) return;
  StopWorkers(p);
  mxtpu_recordio_close(p->reader);
  delete p;
}

}  // extern "C"
