"""``mx.autograd`` — imperative autograd scopes over the functional tape.

Reference: python/mxnet/autograd.py (record/pause/train_mode/predict_mode,
mark_variables, backward, grad) backed by src/imperative/imperative.cc.
Engine here: mxnet_tpu._tape (see its docstring for the jax.vjp design).
"""
from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp

from . import _tape
from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad",
           "get_symbol", "Function", "set_recording", "set_training",
           "register_grad_ready_hook"]


is_recording = _tape.is_recording
is_training = _tape.is_training
set_recording = _tape.set_recording
set_training = _tape.set_training


def register_grad_ready_hook(variable, fn):
    """Register ``fn(ndarray)`` to fire when ``variable``'s gradient is
    finalized by ``backward()`` — in backward order, after grad_req
    write/add applied, so ``.grad`` holds the finished value inside the
    hook.  ``variable`` may be an NDArray or a gluon ``Parameter``.
    Returns a handle with ``remove()``.

    This is the eager half of the backward-overlapped communication
    pipeline (parallel.OverlapScheduler dispatches per-bucket gradient
    collectives from these hooks while backprop is still running)."""
    arr = getattr(variable, "_data", None)
    if not isinstance(arr, NDArray):
        arr = variable
    if not isinstance(arr, NDArray):
        raise MXNetError(
            "register_grad_ready_hook expects an NDArray or an "
            f"initialized Parameter, got {type(variable)}")
    return _tape.register_grad_ready_hook(arr, fn)


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = _tape.set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = _tape.set_training(self._enter_train_mode)
        return self

    def __exit__(self, *exc):
        if self._enter_is_record is not None:
            _tape.set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            _tape.set_training(self._prev_train_mode)
        return False


def record(train_mode=True):
    """with autograd.record(): ... — enables op recording + train mode."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients=None, grad_reqs="write"):
    if isinstance(variables, NDArray):
        variables = [variables]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, req in zip(variables, grad_reqs):
        _tape.mark_variable(v, req)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    _tape.backward(heads, head_grads, retain_graph=retain_graph,
                   train_mode=train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Compute and RETURN grads of heads wrt variables (does not touch .grad).

    Reference: python/mxnet/autograd.py grad(). ``create_graph=True``
    replays the recorded subgraph as a pure jax function and records its
    vjp as one tape op, so the returned grads are differentiable again
    (higher-order; jax differentiates through vjp natively).
    """
    single = isinstance(variables, NDArray)
    var_list = [variables] if single else list(variables)
    if create_graph:
        heads_list = [heads] if isinstance(heads, NDArray) else list(heads)
        if head_grads is None:
            seeds = [jnp.ones(h.shape, h.dtype) for h in heads_list]
        else:
            hg = [head_grads] if isinstance(head_grads, NDArray) \
                else list(head_grads)
            seeds = [g._data for g in hg]
        f = _tape.replay_function(heads_list, var_list)

        def grad_fn(*var_datas):
            _, pull = jax.vjp(f, *var_datas)
            g = pull(tuple(seeds))
            return g if len(var_list) > 1 else g[0]

        from .ndarray.ndarray import apply_nary
        outs = apply_nary(grad_fn, var_list, n_out=len(var_list),
                          name="grad")
        outs = outs if isinstance(outs, list) else [outs]
        return outs[0] if single else outs
    # stash current grads/reqs, run a scoped backward, then restore
    # (grad-ready hooks stay quiet: the scratch _grad state is not a
    # training gradient and must not trigger overlap dispatch)
    saved = [(v._grad, v._grad_req) for v in var_list]
    for v in var_list:
        v._grad = None
        v._grad_req = "write"
    with _tape.suppress_grad_hooks():
        _tape.backward(heads, head_grads, retain_graph=bool(retain_graph),
                       train_mode=train_mode)
    grads = []
    for v, (old_g, old_req) in zip(var_list, saved):
        if v._grad is None:
            raise MXNetError("one of the variables does not participate in "
                             "the graph of heads")
        grads.append(NDArray(v._grad, v._ctx))
        v._grad, v._grad_req = old_g, old_req
    return grads[0] if single else grads


class Function:
    """Custom differentiable function (reference autograd.Function).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def __call__(self, *inputs):
        import jax

        def fwd_raw(*datas):
            nds = [NDArray(d) for d in datas]
            with _RecordingStateScope(False, None):
                out = self.forward(*nds)
            outs = out if isinstance(out, tuple) else (out,)
            return tuple(o.data for o in outs)

        def make_vjp(*datas):
            primal = fwd_raw(*datas)

            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                with _RecordingStateScope(False, None):
                    in_grads = self.backward(*[NDArray(c) for c in cts])
                igs = in_grads if isinstance(in_grads, tuple) else (in_grads,)
                return tuple(g.data for g in igs)
            return primal, vjp_fn

        datas = [x.data for x in inputs]
        if _tape.is_recording():
            primal, vjp_fn = make_vjp(*datas)
            node = _tape.Node(list(inputs), vjp_fn,
                              [type("P", (), {"shape": p.shape, "dtype": p.dtype})()
                               for p in primal],
                              _bump_counter(), name=type(self).__name__)
            outs = [NDArray(p, inputs[0]._ctx) for p in primal]
            for i, o in enumerate(outs):
                o._node = node
                o._out_index = i
        else:
            primal = fwd_raw(*datas)
            outs = [NDArray(p, inputs[0]._ctx) for p in primal]
        return outs[0] if len(outs) == 1 else tuple(outs)

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError


def _bump_counter():
    _tape._STATE.counter += 1
    return _tape._STATE.counter


def get_symbol(x):
    """Rebuild the symbolic graph of a recorded imperative computation
    (reference autograd.get_symbol / MXAutogradGetSymbol): walk the tape
    from ``x`` and compose a Symbol whose nodes carry the recorded
    forward closures. The result lists its leaf inputs as variables
    (``var0``, ``var1``, ... in first-use order), prints/plots through
    mx.viz, and BINDS — executing it replays the recorded ops.

    Requires the graph to still hold its forward functions: call before
    ``backward()`` or use ``backward(retain_graph=True)``. JSON
    round-trips of traced graphs are not supported (closures are not
    serializable); ``hybridize()`` + ``export()`` is the deployment path.
    """
    from .symbol.symbol import Symbol, var as _sym_var
    if not isinstance(x, NDArray):
        raise MXNetError("get_symbol expects an NDArray")
    memo = {}         # id(leaf NDArray) -> var Symbol
    node_memo = {}    # id(tape Node) -> base op Symbol (one per op, so a
                      # multi-output fn executes ONCE however many
                      # outputs are used)
    counter = [0]

    def build(arr):
        node = arr._node
        if node is None:
            key = id(arr)
            if key not in memo:
                memo[key] = _sym_var(f"var{counter[0]}")
                counter[0] += 1
            return memo[key]
        if node.fn is None:
            if node.vjp_fn is not None:
                # Function nodes record a custom vjp, not a replayable
                # forward closure (autograd.Function.__call__)
                raise MXNetError(
                    "get_symbol: the graph contains an autograd.Function "
                    "node, which has no replayable forward closure; "
                    "express that op through nd/gluon ops (or CustomOp) "
                    "to trace it")
            raise MXNetError(
                "get_symbol: the tape was consumed by backward(); "
                "re-run the forward or use backward(retain_graph=True)")
        if id(node) not in node_memo:
            args = [build(inp) for inp in node.inputs]
            node_memo[id(node)] = Symbol(
                "__traced_fn__", args,
                {"_fn": node.fn, "_n_out": node.n_out,
                 "_name": node.name or "op"},
                name=node.name or f"traced{counter[0]}")
        s = node_memo[id(node)]
        if node.n_out > 1:
            s = s[arr._out_index]
        return s

    return build(x)
