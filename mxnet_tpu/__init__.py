"""mxnet_tpu — a TPU-native deep learning framework with MXNet's capabilities.

A ground-up rebuild of the reference (zhanghang1989/incubator-mxnet, an Apache
MXNet 1.x fork) for TPU: jax/XLA/Pallas is the compute substrate, ``jit`` over
``jax.sharding.Mesh`` is the scaling substrate, and the public API keeps
MXNet's imperative NDArray + Gluon + KVStore surface so reference users can
switch with a context change (``mx.tpu()``).

Usage mirrors the reference::

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(10))
    net.initialize(ctx=mx.tpu())
    net.hybridize()            # -> jax.jit (XLA) instead of CachedOp
    with autograd.record():
        loss = ...
    loss.backward()
    trainer.step(batch_size)

Layer map vs the reference is documented in SURVEY.md §1; every reference
component's disposition is in SURVEY.md §2.1.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os
if _os.environ.get("MXTPU_LHS", "0") == "1":
    # XLA latency-hiding scheduler (backward-overlapped comm, ISSUE 5):
    # XLA_FLAGS must be set before the backend initializes, i.e. before
    # anything below runs a jax computation
    from .runtime import apply_lhs_flags as _apply_lhs_flags
    _apply_lhs_flags()

from ._dist_init import maybe_init_distributed as _maybe_init_distributed
_maybe_init_distributed()   # must precede any jax computation

from . import debug
debug._install()            # MXTPU_DEBUG_NANS / MXTPU_ENFORCE_DETERMINISM
                            # must configure jax before any computation

from .base import MXNetError, NotSupportedError
from . import telemetry   # first: every subsystem below publishes to it
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, \
    num_gpus, num_tpus
from . import ndarray
from . import ndarray as nd
from .ndarray import random
debug._seed_from_env()      # MXTPU_SEED: reproducible driver runs
from . import autograd
from . import initializer
from . import initializer as init
from . import optimizer
from .optimizer import lr_scheduler
from . import metric
from . import gluon
from . import kvstore as kv
from .kvstore import create as _kv_create
from . import io
from . import recordio
from . import callback
from . import profiler
from . import runtime
from . import util
from . import test_utils
from . import symbol
from . import symbol as sym
from .symbol import AttrScope
from . import module
from . import operator
from . import module as mod
from . import visualization as viz
from . import name
from . import attribute
from . import engine
from . import rtc
from . import rnn
from . import monitor
from .monitor import Monitor
from . import model
from . import image
from . import parallel
from . import lint
from . import checkpoint
from . import serving
from . import elastic

# mx.np / mx.npx numpy-compat front end (SURVEY.md §2.2 numpy-compat row):
# jax.numpy already provides numpy semantics; expose it under the mx.np name.
import jax.numpy as np  # noqa: F401
from . import npx  # noqa: F401
from . import amp  # noqa: F401
from . import contrib  # noqa: F401


def __getattr__(name):
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
