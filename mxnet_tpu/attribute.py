"""``mx.attribute`` — attribute scoping for symbols.

Reference: python/mxnet/attribute.py (AttrScope). The implementation lives
with the Symbol facade (symbol/symbol.py AttrScope — ctx_group etc. survive
the json round-trip); this module provides the reference import path.
"""
from .symbol.symbol import AttrScope  # noqa: F401

__all__ = ["AttrScope"]
