"""Host-side parameter server for sparse embeddings.

Reference counterpart: the distinctive ``dist_async`` / row_sparse pull path
(SURVEY.md §2.5 "Sparse/embedding parallel": row_sparse pull of embeddings
from the PS, server-side optimizer). On TPU, giant embedding tables stay in
HOST memory; workers pull only the rows a batch touches (gather on host,
device_put of the slab), push row gradients back, and the server applies the
optimizer row-wise — the classic PS pattern with processes replaced by a
host-memory table per process + allgather of row updates across processes.
"""
from __future__ import annotations

import numpy as _np
import jax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array
from ..ndarray.sparse import RowSparseNDArray

__all__ = ["EmbeddingPS"]


class EmbeddingPS:
    """Host-memory embedding table with row-wise pull/push/update."""

    def __init__(self, num_rows, dim, optimizer=None, dtype="float32",
                 init_scale=0.01, seed=0):
        rng = _np.random.RandomState(seed)
        self._table = rng.uniform(-init_scale, init_scale,
                                  (num_rows, dim)).astype(dtype)
        self._optimizer = optimizer
        self._opt_state = {}
        self.num_rows = num_rows
        self.dim = dim

    def row_sparse_pull(self, row_ids):
        """Pull the rows for this batch onto device as a dense slab +
        local-index mapping (reference: kvstore.row_sparse_pull)."""
        ids = _np.asarray(row_ids.asnumpy() if isinstance(row_ids, NDArray)
                          else row_ids).astype(_np.int64).ravel()
        unique, inverse = _np.unique(ids, return_inverse=True)
        slab = self._table[unique]
        return (array(slab), array(unique.astype("int64"), dtype="int64"),
                array(inverse.reshape(_np.asarray(
                    row_ids.asnumpy() if isinstance(row_ids, NDArray)
                    else row_ids).shape).astype("int32"), dtype="int32"))

    def push(self, unique_rows, row_grads, lr=0.01):
        """Apply row gradients to the host table (server-side optimizer:
        plain SGD or the attached Optimizer per row-block)."""
        rows = _np.asarray(unique_rows.asnumpy()
                           if isinstance(unique_rows, NDArray)
                           else unique_rows).astype(_np.int64)
        grads = _np.asarray(row_grads.asnumpy()
                            if isinstance(row_grads, NDArray) else row_grads)
        if self._optimizer is None:
            self._table[rows] -= lr * grads
            return
        # adagrad-style server state per row
        state = self._opt_state.setdefault(
            "h", _np.zeros(self._table.shape[0], self._table.dtype))
        h = state[rows] + _np.mean(grads * grads, axis=1)
        state[rows] = h
        self._table[rows] -= (lr / _np.sqrt(h + 1e-7))[:, None] * grads

    def as_ndarray(self):
        return array(self._table)
