"""Bucketed sharded gradient sync + ZeRO-1 optimizer-state sharding.

The data-parallel hot path used to sync gradients with a full-replica
``lax.psum`` and keep a full optimizer-state copy on every chip.  This
module provides the pieces that replace it (ISSUE 3 tentpole):

- :class:`BucketPlan` — host-side planning that flattens all eligible
  parameters into a few size-bounded flat f32 buckets
  (``MXTPU_COMM_BUCKET_MB``, default 32), so the per-step collectives
  are few and large instead of one small ring per tensor (the
  BIGARRAY_BOUND coalescing idea, applied in-graph).
- :func:`reduce_scatter_bucket` — the per-bucket gradient collective,
  run inside ``shard_map`` over the ``dp`` axis: each chip contributes
  its *local* gradient and receives only its 1/N shard of the mean —
  a true reduce-scatter, optionally with the payload quantized on the
  wire (``MXTPU_COMM_DTYPE=bf16|int8``; int8 is stochastic-rounding
  with one scale per (chip, bucket), EQuARX-style — arXiv:2506.17615,
  PAPERS.md row 9).  The updated-parameter all-gather that completes
  the ZeRO-1 pipeline is a plain ``lax.all_gather`` (params must come
  back exact; only the gradient payload is quantizable).
- :func:`comm_block` — the ``comm`` observability schema shared by
  ``bench.py`` / ``tools/bench_pipeline.py`` / the parity tests, so the
  shape is regression-tested in tier-1 even on CPU (zeros are fine).

ZeRO-1 memory math (fp32, N = dp size): momentum-SGD keeps 4 B/param of
optimizer state, Adam 8 B/param — replicated on every chip before; with
the bucket shards each chip holds 1/N of it (plus its 1/N update
compute).  Parameters stay replicated (ZeRO *stage 1*).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .mesh import AXIS_DP

__all__ = ["BucketPlan", "bucket_bound_bytes", "comm_dtype",
           "sharded_sync_enabled", "overlap_comm_enabled",
           "reduce_scatter_bucket", "quantize_int8", "dequantize_int8",
           "int8_roundtrip_error", "comm_block", "ZERO1_RULES"]

#: fused-rule kernels that are elementwise in the parameter, so the
#: update can run on an arbitrary flat shard of the bucket.  lamb/lars
#: need per-parameter norms and keep the replicated psum path.
ZERO1_RULES = frozenset({"sgd", "nag", "adam", "adamw", "rmsprop"})


def bucket_bound_bytes():
    """Bucket size bound in bytes (``MXTPU_COMM_BUCKET_MB``, default 32)."""
    return int(float(os.environ.get("MXTPU_COMM_BUCKET_MB", "32"))
               * 1024 * 1024)


def comm_dtype():
    """Wire dtype for the gradient reduce-scatter: ``"fp32"`` (default),
    ``"bf16"`` or ``"int8"`` via ``MXTPU_COMM_DTYPE``."""
    mode = os.environ.get("MXTPU_COMM_DTYPE", "fp32").lower() or "fp32"
    if mode not in ("fp32", "float32", "bf16", "bfloat16", "int8"):
        raise MXNetError(
            f"MXTPU_COMM_DTYPE={mode!r}: expected fp32|bf16|int8")
    return {"float32": "fp32", "bfloat16": "bf16"}.get(mode, mode)


def sharded_sync_enabled():
    """Kill switch: ``MXTPU_SHARDED_SYNC=0`` forces the legacy full
    psum + replicated-update path even when ``shard_updates=True``."""
    return os.environ.get("MXTPU_SHARDED_SYNC", "1") != "0"


def overlap_comm_enabled():
    """Backward-overlapped gradient communication (ISSUE 5 tentpole):
    ``MXTPU_OVERLAP_COMM=0`` kills the overlap — bucket plans fall back
    to declaration-order fill and the eager OverlapScheduler stands
    down, reproducing the PR 3 monolithic-sync behavior bitwise."""
    return os.environ.get("MXTPU_OVERLAP_COMM", "1") != "0"


class BucketPlan:
    """Greedy coalescing of parameter tensors into flat f32 buckets.

    Parameters are filled in order into buckets of at most
    ``bound_bytes`` of f32 payload (a single tensor larger than the
    bound gets its own bucket), and every bucket is zero-padded so its
    flat length divides ``dp`` — each chip's shard is exactly
    ``length // dp`` elements, no edge-chip special case.

    ``fill_order`` (ISSUE 5 tentpole) is a permutation of parameter
    indices in expected *backward gradient-ready* order
    (reverse-topological: parameters used last in the forward first).
    Buckets are filled in that order, so during backprop bucket 0's
    gradients finish first, bucket 1's next, ... — each bucket's
    reduce-scatter can launch while the rest of the backward is still
    computing (:attr:`ready_order`).  ``None`` keeps declaration-order
    fill (the PR 3 monolithic layout; ``MXTPU_OVERLAP_COMM=0``).
    """

    def __init__(self, shapes, dp, bound_bytes=None, fill_order=None):
        if dp < 1:
            raise MXNetError(f"BucketPlan: dp must be >= 1, got {dp}")
        bound = bound_bytes if bound_bytes is not None \
            else bucket_bound_bytes()
        bound_elems = max(1, bound // 4)          # f32 on-wire elements
        self.dp = int(dp)
        self.shapes = [tuple(s) for s in shapes]
        sizes = []
        for s in self.shapes:
            n = 1
            for d in s:
                n *= int(d)
            sizes.append(n)
        self.sizes = sizes
        if fill_order is None:
            order = list(range(len(sizes)))
            self.fill_order = None
        else:
            order = [int(i) for i in fill_order]
            if sorted(order) != list(range(len(sizes))):
                raise MXNetError(
                    f"BucketPlan: fill_order must be a permutation of "
                    f"0..{len(sizes) - 1}, got {fill_order!r}")
            self.fill_order = tuple(order)
        self.buckets = []          # list of lists of param indices
        cur, cur_n = [], 0
        for i in order:
            n = sizes[i]
            if cur and cur_n + n > bound_elems:
                self.buckets.append(cur)
                cur, cur_n = [], 0
            cur.append(i)
            cur_n += n
        if cur:
            self.buckets.append(cur)
        self.lengths = []          # padded flat length per bucket
        self.offsets = [None] * len(sizes)   # (bucket_id, offset)
        for b, idxs in enumerate(self.buckets):
            off = 0
            for i in idxs:
                self.offsets[i] = (b, off)
                off += sizes[i]
            pad = (-off) % self.dp
            self.lengths.append(off + pad)

    @property
    def n_buckets(self):
        return len(self.buckets)

    @property
    def ready_order(self):
        """Bucket ids in backward gradient-completion order.  Buckets are
        created in fill order, so when the plan was built with a
        backward ``fill_order`` this is simply ``(0, 1, ...)`` — bucket 0
        completes (and can launch its reduce-scatter) first.  Without a
        ``fill_order`` completion order is unknown; the same tuple is
        returned as the monolithic-dispatch order."""
        return tuple(range(self.n_buckets))

    def shard_length(self, b):
        return self.lengths[b] // self.dp

    def param_span(self, i):
        """``(bucket_id, offset, size)`` of parameter ``i``'s span in
        bucket space — the state-resharding primitive
        (docs/FAULT_TOLERANCE.md): checkpoint save slices bucket-space
        optimizer-state vectors back to per-parameter arrays with this,
        and restore re-flattens them into whatever dp size's plan the
        resumed run built (padding never hits disk)."""
        b, off = self.offsets[i]
        return b, off, self.sizes[i]

    def flatten(self, arrays):
        """Per-bucket flat f32 arrays (concat in plan order + zero pad)."""
        out = []
        for b, idxs in enumerate(self.buckets):
            parts = [jnp.ravel(arrays[i]).astype(jnp.float32)
                     for i in idxs]
            flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            pad = self.lengths[b] - flat.shape[0]
            if pad:
                flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
            out.append(flat)
        return out

    def unflatten(self, flats, like):
        """Inverse of :meth:`flatten`: per-parameter arrays with the
        shapes of the plan and the dtypes of ``like``."""
        out = [None] * len(self.shapes)
        for i, (b, off) in enumerate(self.offsets):
            n = self.sizes[i]
            out[i] = flats[b][off:off + n].reshape(self.shapes[i]) \
                .astype(like[i].dtype)
        return out

    # -- wire accounting (static, per step) -----------------------------
    def grad_bytes_fp32(self):
        return 4 * sum(self.lengths)

    def wire_bytes(self, mode):
        """Per-chip gradient payload put on the wire by one reduce-
        scatter round, after quantization."""
        per_elem = {"fp32": 4, "bf16": 2, "int8": 1}[mode]
        scales = 4 * self.n_buckets if mode == "int8" else 0
        return per_elem * sum(self.lengths) + scales


# ---------------------------------------------------------------------------
# quantization (int8, stochastic rounding, one scale per chip x bucket)
# ---------------------------------------------------------------------------

# the SR core moved to ops/quant_matmul (ISSUE 20): the wire (this
# module) and the training-compute path share ONE unbiased rounding
# implementation; these names stay importable here for PR 3 callers.
from ..ops.quant_matmul import (quantize_sr_int8 as quantize_int8,  # noqa: E402,F401
                                dequantize_int8)


def int8_roundtrip_error(flat, key):
    """Measured (not assumed) per-bucket max relative quantization error
    ``max|deq - x| / max|x|`` — the number the parity test reports."""
    q, scale = quantize_int8(flat, key)
    err = jnp.max(jnp.abs(dequantize_int8(q, scale) - flat))
    return err / jnp.maximum(jnp.max(jnp.abs(flat)), 1e-30)


def reduce_scatter_bucket(flat, key, dp, mode="fp32",
                          axis=AXIS_DP):
    """Mean-reduce one bucket across ``dp`` chips, returning this chip's
    1/dp shard.  Must run inside ``shard_map`` with ``axis`` bound;
    ``flat`` is the chip's LOCAL gradient bucket (f32, length % dp == 0).

    - ``fp32``: ``lax.psum_scatter`` (the plain reduce-scatter).
    - ``bf16``: payload cast to bf16 before the collective (half the
      wire bytes; accumulation happens in bf16 — measured error, not
      assumed: see tests/test_sharded_sync.py).
    - ``int8``: stochastic-rounding int8 codes with a per-(chip,bucket)
      f32 scale, exchanged shard-to-shard via ``all_to_all`` (1/4 the
      f32 wire bytes), then dequantized and accumulated in f32 — the
      wire carries int8 but no int8 arithmetic ever overflows.
    """
    if mode == "fp32":
        return lax.psum_scatter(flat, axis, tiled=True) / dp
    if mode == "bf16":
        # bf16 keeps f32's exponent range, so the wire cast needs no
        # amax scale — exempt from the HB21 scaled-cast discipline
        shard = lax.psum_scatter(
            flat.astype(jnp.bfloat16),  # mxlint: disable=HB21
            axis, tiled=True)
        return shard.astype(jnp.float32) / dp
    if mode == "int8":
        q, scale = quantize_int8(flat, key)
        # (dp, L/dp) int8: row j goes to chip j; after all_to_all each
        # chip holds every peer's codes for its own shard
        q = lax.all_to_all(q.reshape(dp, -1), axis, split_axis=0,
                           concat_axis=0, tiled=False)
        scales = lax.all_gather(scale, axis, tiled=False)   # (dp,)
        deq = jnp.sum(q.astype(jnp.float32) * scales.reshape(dp, 1),
                      axis=0)
        return deq / dp
    raise MXNetError(f"unknown comm dtype {mode!r}")


# ---------------------------------------------------------------------------
# the `comm` observability block (bench.py / tools/bench_pipeline.py)
# ---------------------------------------------------------------------------

def comm_block(dp=1, wire_dtype="fp32", buckets=0, bucket_mb=None,
               bytes_reduced_per_step=0, bytes_gathered_per_step=0,
               grad_bytes_fp32=0, collective_ms=None, est_ici_gb_s=None,
               overlap_efficiency=None, zero1=False,
               state_bytes_per_chip=0, state_bytes_replicated=0,
               overlap_comm=False, exposed_comm_ms=None,
               overlap_frac=None):
    """The per-step ``comm`` block schema.  Every field is always
    present so tier-1 regression-tests the shape
    (tests/test_bench_line.py) without needing a multichip host — but
    MEASURED fields (``collective_ms``, ``est_ici_gb_s``,
    ``overlap_efficiency``, ``exposed_comm_ms``, ``overlap_frac``) are
    ``null`` when nothing was measured (CPU / dp=1 / probe skipped)
    instead of 0: the rounds-4/5 silent CPU fallback taught us that a
    zero in a measured field reads as "measured: no comm cost", which
    is a lie (ISSUE 6 honesty fix).  Static wire accounting stays
    integer-zeros — those are genuinely computed, not measured.

    ``exposed_comm_ms`` / ``overlap_frac`` (ISSUE 5) come from the
    with-vs-without-overlap probe
    (``DataParallelTrainer.overlap_probe``): exposed = time the
    overlapped step still spends on communication beyond its pure
    compute, overlap_frac = 1 - exposed / total serialized comm."""
    def _r(x, n):
        return None if x is None else round(float(x), n)

    return {
        "zero1": bool(zero1),
        "dp": int(dp),
        "wire_dtype": str(wire_dtype),
        "buckets": int(buckets),
        "bucket_mb": float(bucket_mb if bucket_mb is not None
                           else bucket_bound_bytes() / (1024 * 1024)),
        "bytes_reduced_per_step": int(bytes_reduced_per_step),
        "bytes_gathered_per_step": int(bytes_gathered_per_step),
        "grad_bytes_fp32": int(grad_bytes_fp32),
        "collective_ms": _r(collective_ms, 3),
        "est_ici_gb_s": _r(est_ici_gb_s, 2),
        "overlap_efficiency": _r(overlap_efficiency, 4),
        "overlap_comm": bool(overlap_comm),
        "exposed_comm_ms": _r(exposed_comm_ms, 3),
        "overlap_frac": _r(overlap_frac, 4),
        "state_bytes_per_chip": int(state_bytes_per_chip),
        "state_bytes_replicated": int(state_bytes_replicated),
    }
