"""``mxnet_tpu.parallel`` — the TPU scaling substrate.

This package is NEW capability relative to the reference (SURVEY.md §2.5):
the reference scaled via KVStore push/pull (data parallel only); here
scaling is mesh-sharded jit:

  - mesh.py:           device mesh construction (dp/tp/pp/sp axes), single- or
                       multi-host, `jax.distributed` init from DMLC_*-style env;
                       MeshConfig — the ONE named-axis dp x tp x pp config
                       (MXTPU_MESH) every hot path consumes (ISSUE 11,
                       docs/PARALLELISM.md); AXIS_DP/TP/PP constants (lint
                       HB17 bans literal copies)
  - data_parallel.py:  DataParallelTrainer — the fused jit train step with
                       in-graph grad psum over the 'dp' axis (replaces
                       kvstore push/pull on the hot path, SURVEY.md §7)
  - tensor_parallel.py: megatron-style PartitionSpec annotations for Dense/
                       Embedding/attention weights over the 'tp' axis
  - ring_attention.py: shard_map ring attention over the 'sp' axis for
                       long-context (SURVEY.md §5.7)
  - ps.py:             host-side parameter server for sparse embeddings
                       (row_sparse pull — the reference's distinctive
                       dist_async capability, §2.5 last row)
"""
from .mesh import (make_mesh, local_mesh, distributed_init, mesh_scope,
                   current_mesh, data_sharding, replicate_sharding,
                   batch_sharding, MeshConfig, mesh_config_from_env,
                   parallelism_block, AXIS_DP, AXIS_TP, AXIS_PP)
from .data_parallel import DataParallelTrainer, all_reduce_gradients
from .overlap import OverlapScheduler
from .tensor_parallel import (shard_params_tp, tp_spec_for_param,
                              ParallelDense, ParallelEmbedding,
                              llama_tp_rules, bert_tp_rules,
                              shard_model_tp)
from .ring_attention import ring_attention, ring_attention_local, \
    sequence_parallel_attention
from .ulysses import ulysses_attention, ulysses_sequence_parallel_attention
from .pipeline_parallel import (pipeline_apply, stack_stage_params,
                                Pipeline, one_f_one_b_schedule,
                                bubble_fraction, split_into_stages,
                                PipelineStageExecutor)
from .moe import moe_apply, MoEDense, load_balance_loss
from . import ps
