"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

Reference capability (SURVEY.md §2.5 "EP/MoE" row — absent upstream as a
first-class layer, present here because MoE is a headline TPU workload).
GShard-style top-k routing with static capacity: dispatch/combine are
einsums over a (tokens, experts, capacity) one-hot, so every shape is
static and XLA shards the expert dimension over 'ep' — the all-to-all
falls out of the sharding algebra instead of being hand-written.

Functional core (``moe_apply``) + a gluon ``MoEDense`` block whose expert
weights carry a ``P('ep', ...)`` shard spec for the fused trainer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["moe_apply", "MoEDense", "load_balance_loss"]


def _top1_dispatch(logits, capacity):
    """Top-1 routing with static capacity (GShard §3.2).

    logits: (T, E). Returns dispatch (T, E, C) float 0/1, combine
    (T, E, C) float (gate-weighted dispatch), plus aux tensors for the
    load-balancing loss.
    """
    T, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)          # (T, E)
    expert = jnp.argmax(gates, axis=-1)              # (T,)
    gate = jnp.take_along_axis(gates, expert[:, None], axis=-1)[:, 0]
    mask = jax.nn.one_hot(expert, E)                 # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(mask, axis=0) * mask            # 1-based where routed
    keep = (pos <= capacity) & (mask > 0)            # drop overflow tokens
    pos0 = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
    dispatch = (keep[..., None] *
                jax.nn.one_hot(pos0, capacity)).astype(logits.dtype)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, gates, mask


def load_balance_loss(gates, mask):
    """GShard aux loss: E * sum_e (mean gate_e * mean routed_e)."""
    E = gates.shape[-1]
    density = jnp.mean(mask, axis=0)                 # fraction routed
    density_proxy = jnp.mean(gates, axis=0)          # mean gate prob
    return E * jnp.sum(density * density_proxy)


def moe_apply(x, router_w, w_up, w_down, *, capacity_factor=1.25,
              activation=jax.nn.gelu):
    """Top-1 MoE FFN over tokens.

    x: (T, d); router_w: (d, E); w_up: (E, d, h); w_down: (E, h, d).
    Returns (y (T, d), aux_loss scalar). Under jit with w_up/w_down sharded
    P('ep', ...) the per-expert einsums shard over 'ep' and XLA inserts the
    dispatch all-to-all.
    """
    T, d = x.shape
    E = router_w.shape[-1]
    capacity = max(1, int(capacity_factor * T / E))
    logits = x @ router_w                            # (T, E)
    dispatch, combine, gates, mask = _top1_dispatch(logits, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)     # (E, C, d)
    h = activation(jnp.einsum("ecd,edh->ech", expert_in, w_up))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w_down)     # (E, C, d)
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y, load_balance_loss(gates, mask)


class MoEDense:
    """Gluon-flavoured MoE FFN block (functional params, shard-spec'd).

    Deliberately NOT a HybridBlock: MoE lives inside fused jitted steps
    (DataParallelTrainer / llama), where parameters flow functionally. Use
    ``init_params(key)`` then ``apply(params, x)``; ``shard_specs()`` gives
    the 'ep' PartitionSpecs for each weight.
    """

    def __init__(self, hidden_size, ffn_size, num_experts,
                 capacity_factor=1.25):
        if num_experts < 1:
            raise MXNetError("num_experts must be >= 1")
        self.hidden_size = hidden_size
        self.ffn_size = ffn_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor

    def init_params(self, key):
        kr, ku, kd = jax.random.split(key, 3)
        d, h, E = self.hidden_size, self.ffn_size, self.num_experts
        scale = d ** -0.5
        return {
            "router": jax.random.normal(kr, (d, E)) * scale,
            "w_up": jax.random.normal(ku, (E, d, h)) * scale,
            "w_down": jax.random.normal(kd, (E, h, d)) * (h ** -0.5),
        }

    def shard_specs(self, axis="ep"):
        return {
            "router": P(),
            "w_up": P(axis, None, None),
            "w_down": P(axis, None, None),
        }

    def apply(self, params, x):
        """x: (..., d) — flattened to tokens internally."""
        lead = x.shape[:-1]
        tokens = x.reshape(-1, x.shape[-1])
        y, aux = moe_apply(tokens, params["router"], params["w_up"],
                           params["w_down"],
                           capacity_factor=self.capacity_factor)
        return y.reshape(lead + (x.shape[-1],)), aux
