"""Ring attention: sequence/context parallelism over the mesh 'sp' axis.

NEW capability vs the reference (SURVEY.md §5.7: absent upstream; required
for the long-context/Llama stretch). Design:

  - the sequence axis of Q/K/V is sharded over 'sp'
  - inside shard_map, each device holds its Q block and rotates K/V blocks
    around the ring with lax.ppermute (ICI neighbour exchanges), accumulating
    attention with the numerically-stable running-max/denominator update
    (flash-attention style), so no device ever materializes the full
    (T x T) score matrix
  - causal masking is applied per (q_block, kv_block) pair from ring offsets

This composes with tp ('tp' on heads) and dp in one mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["ring_attention", "sequence_parallel_attention"]


def _block_attn(q, k, v, bias, scale):
    """Standard attention for one (q_block, kv_block) pair, returning
    (unnormalized out, row max, row denom) for streaming combination."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def _combine(o1, m1, l1, o2, m2, l2):
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return o1 * a1 + o2 * a2, m, l1 * a1 + l2 * a2


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None):
    """q/k/v: (B, H, T, D) jax.Arrays with T sharded over `axis_name`.

    Returns attention output with the same sharding. Collective cost per
    ring step: one neighbour ppermute of the local K/V block — bandwidth
    optimal on an ICI ring (PAPERS.md: 'Exploring the limits of Concurrency
    in ML Training on Google TPUs' motivates overlapping these sends with
    the block compute; XLA pipelines the ppermute against einsum here).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n = mesh.shape[axis_name]

    def local_fn(q_blk, k_blk, v_blk):
        idx = lax.axis_index(axis_name)
        t_q = q_blk.shape[2]

        def make_bias(kv_rank):
            if not causal:
                return None
            # global positions: q rows at idx*t_q, kv cols at kv_rank*t_k
            t_k = k_blk.shape[2]
            q_pos = idx * t_q + jnp.arange(t_q)
            k_pos = kv_rank * t_k + jnp.arange(t_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            return jnp.where(mask, 0.0, -1e30)[None, None]

        o, m, l = _block_attn(q_blk, k_blk, v_blk, make_bias(idx), scale)

        def body(i, carry):
            o, m, l, k_cur, v_cur = carry
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
            kv_rank = (idx - i - 1) % n
            bias = None
            if causal:
                t_k = k_cur.shape[2]
                q_pos = idx * t_q + jnp.arange(t_q)
                k_pos = kv_rank * t_k + jnp.arange(t_k)
                mask = q_pos[:, None] >= k_pos[None, :]
                bias = jnp.where(mask, 0.0, -1e30)[None, None]
            o2, m2, l2 = _block_attn(q_blk, k_cur, v_cur, bias, scale)
            o, m, l = _combine(o, m, l, o2, m2, l2)
            return (o, m, l, k_cur, v_cur)

        o, m, l, _, _ = lax.fori_loop(0, n - 1, body, (o, m, l, k_blk, v_blk))
        return o / jnp.maximum(l, 1e-30)

    spec = P(None, None, axis_name, None)
    sharding = jax.sharding.NamedSharding(mesh, spec)

    # Eager arrays committed to one device are laid out over the mesh
    # first (and the output restored to the caller's layout so eager CP
    # composes with unsharded surrounding ops); under jit the constraint
    # is compiled in and the output stays sequence-sharded.
    eager = not isinstance(q, jax.core.Tracer)
    restore = None

    def place(x):
        if isinstance(x, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(x, sharding)
        return jax.device_put(x, sharding)

    if eager and getattr(q, "sharding", None) is not None and \
            not q.sharding.is_equivalent_to(sharding, q.ndim):
        restore = q.sharding
    q, k, v = place(q), place(k), place(v)
    out = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)(q, k, v)
    if restore is not None:
        out = jax.device_put(out, restore)
    return out


def sequence_parallel_attention(q, k, v, mesh=None, axis_name="sp",
                                causal=True, scale=None):
    """NDArray-level wrapper: gluon attention layers call this when a mesh
    with an 'sp' axis is ambient (exposed as
    gluon.contrib.nn.SelfAttention(context_parallel=True))."""
    from ..ndarray.ndarray import NDArray, apply_nary
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if mesh is None or axis_name not in mesh.shape:
        raise MXNetError("sequence_parallel_attention needs an ambient mesh "
                         f"with a '{axis_name}' axis")

    def fn(qa, ka, va):
        return ring_attention(qa, ka, va, mesh, axis_name, causal, scale)
    return apply_nary(fn, [q, k, v], name="ring_attention")
