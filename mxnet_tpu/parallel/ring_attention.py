"""Ring attention: sequence/context parallelism over the mesh 'sp' axis.

NEW capability vs the reference (SURVEY.md §5.7: absent upstream; required
for the long-context/Llama stretch). Design:

  - the sequence axis of Q/K/V is sharded over 'sp'
  - inside shard_map, each device holds its Q block and rotates K/V blocks
    around the ring with lax.ppermute (ICI neighbour exchanges), accumulating
    attention with the numerically-stable running-max/denominator update
    (flash-attention style), so no device ever materializes the full
    (T x T) score matrix
  - causal masking is applied per (q_block, kv_block) pair from ring offsets

This composes with tp ('tp' on heads) and dp in one mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["ring_attention", "ring_attention_local",
           "sequence_parallel_attention"]


def _block_attn(q, k, v, causal, scale):
    """Attention over one (q_block, kv_block) pair via the BLOCKWISE
    streaming kernel (ops.flash_attention._scan_forward): per-device
    memory O(T_local * bk), never the (T_local, T_local) score matrix —
    the flash x ring composition (SURVEY.md §5.7 TPU plan). Returns
    (normalized out, logsumexp) for exact cross-block combination."""
    from ..ops.flash_attention import _pick_block, _scan_forward
    b, h, t, d = q.shape
    lk = k.shape[2]
    bk = _pick_block(lk, 256) or lk
    out, lse = _scan_forward(q.reshape(b * h, t, d),
                             k.reshape(b * h, lk, d),
                             v.reshape(b * h, lk, d), causal, scale, bk)
    return (out.reshape(b, h, t, d),
            lse.reshape(b, h, t))


def _combine(o1, lse1, o2, lse2):
    """Exact merge of two normalized partial attentions via logsumexp;
    a fully-masked block (lse=-inf) contributes exactly zero."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return o1 * w1 + o2 * w2, lse


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None):
    """q/k/v: (B, H, T, D) jax.Arrays with T sharded over `axis_name`.

    Returns attention output with the same sharding. Collective cost per
    ring step: one neighbour ppermute of the local K/V block — bandwidth
    optimal on an ICI ring (PAPERS.md: 'Exploring the limits of Concurrency
    in ML Training on Google TPUs' motivates overlapping these sends with
    the block compute; XLA pipelines the ppermute against einsum here).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n = mesh.shape[axis_name]

    def local_fn(q_blk, k_blk, v_blk):
        return ring_attention_local(q_blk, k_blk, v_blk, axis_name, n,
                                    causal=causal, scale=scale)

    return _shard_mapped_qkv(local_fn, q, k, v, mesh, axis_name)


def ring_attention_local(q_blk, k_blk, v_blk, axis_name, n_shards,
                         causal=False, scale=None):
    """Ring-attention body for use INSIDE an existing shard_map whose mesh
    binds ``axis_name`` — this is what makes CP composable with dp/tp/pp in
    one SPMD program (e.g. a pipelined stage function that is itself inside
    a dp x tp x sp x pp shard_map). ``ring_attention`` wraps it in its own
    shard_map for standalone use.

    q/k/v blocks: (B, H_local, T_local, D) — this device's sequence shard.
    """
    if scale is None:
        scale = 1.0 / (q_blk.shape[-1] ** 0.5)
    n = n_shards
    idx = lax.axis_index(axis_name)

    # ring step 0 is always the DIAGONAL pair: in-block causal mask
    # handled inside the streaming kernel itself
    o, lse = _block_attn(q_blk, k_blk, v_blk, causal, scale)

    def body(i, carry):
        o, lse, k_cur, v_cur = carry
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        kv_rank = (idx - i - 1) % n
        # off-diagonal pairs are all-or-nothing under causal: past
        # blocks attend fully, future blocks are nulled via lse=-inf
        # (uniform compute keeps the ring SPMD)
        o2, lse2 = _block_attn(q_blk, k_cur, v_cur, False, scale)
        if causal:
            lse2 = jnp.where(kv_rank < idx, lse2,
                             jnp.full_like(lse2, -1e30))
        o, lse = _combine(o, lse, o2, lse2)
        return (o, lse, k_cur, v_cur)

    o, lse, _, _ = lax.fori_loop(0, n - 1, body, (o, lse, k_blk, v_blk))
    # the logsumexp weights are f32; keep the caller's dtype (bf16
    # AMP long-context is exactly this kernel's use case)
    return o.astype(q_blk.dtype)


def _shard_mapped_qkv(local_fn, q, k, v, mesh, axis_name):
    """Shared CP scaffolding (ring + ulysses): sequence-shard q/k/v over
    `axis_name`, run `local_fn` under shard_map, restore the caller's
    layout for eager inputs.

    Eager arrays committed to one device are laid out over the mesh
    first (and the output restored to the caller's layout so eager CP
    composes with unsharded surrounding ops); under jit the constraint
    is compiled in and the output stays sequence-sharded."""
    spec = P(None, None, axis_name, None)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    eager = not isinstance(q, jax.core.Tracer)
    restore = None

    def place(x):
        if isinstance(x, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(x, sharding)
        return jax.device_put(x, sharding)

    if eager and getattr(q, "sharding", None) is not None and \
            not q.sharding.is_equivalent_to(sharding, q.ndim):
        restore = q.sharding
    q, k, v = place(q), place(k), place(v)
    out = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)(q, k, v)
    if restore is not None:
        out = jax.device_put(out, restore)
    return out


def sequence_parallel_attention(q, k, v, mesh=None, axis_name="sp",
                                causal=True, scale=None):
    """NDArray-level wrapper: gluon attention layers call this when a mesh
    with an 'sp' axis is ambient (exposed as
    gluon.contrib.nn.SelfAttention(context_parallel=True))."""
    from ..ndarray.ndarray import NDArray, apply_nary
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if mesh is None or axis_name not in mesh.shape:
        raise MXNetError("sequence_parallel_attention needs an ambient mesh "
                         f"with a '{axis_name}' axis")

    def fn(qa, ka, va):
        return ring_attention(qa, ka, va, mesh, axis_name, causal, scale)
    return apply_nary(fn, [q, k, v], name="ring_attention")
