"""Ulysses-style sequence parallelism: all-to-all head scatter.

NEW capability vs the reference (SURVEY §5.7 names it as the alternative
to ring attention — "Ulysses-style all-to-all head scatter"; DeepSpeed
Ulysses, arXiv:2309.14509, is the public origin of the pattern).

Layout dance (per shard_map device, seq sharded over 'sp' of size S):

    (B, H, T/S, D)  --all_to_all-->  (B, H/S, T, D)
        attention over the FULL sequence on an H/S head slice
    (B, H/S, T, D)  --all_to_all-->  (B, H, T/S, D)

vs ring attention: 2 all-to-alls of the whole activation per layer
(bandwidth-optimal on all-to-all-friendly fabrics) instead of S-1
neighbour K/V hops; causal masking is exact-local because every device
sees the full sequence; head count must be divisible by S. The local
attention runs the same blockwise streaming kernel as the ring path (the
Pallas flash kernel on TPU), so no (T, T) score tensor either way.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from .ring_attention import _block_attn, _shard_mapped_qkv

__all__ = ["ulysses_attention", "ulysses_sequence_parallel_attention"]


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=False,
                      scale=None):
    """q/k/v: (B, H, T, D) with T sharded over `axis_name`; returns the
    attention output with the same sharding. K/V may carry fewer (GQA)
    heads — they are repeated AFTER the head-scatter, so the all-to-alls
    move only the true kv payload."""
    n = mesh.shape[axis_name]
    b, h, t, d = q.shape
    if h % n:
        raise MXNetError(
            f"ulysses_attention: heads ({h}) must divide by the "
            f"'{axis_name}' axis size ({n}); use ring_attention otherwise")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    kv_h = k.shape[1]
    if kv_h % n or h % kv_h:
        raise MXNetError(
            f"ulysses_attention: kv heads ({kv_h}) must divide by "
            f"'{axis_name}' ({n}) and divide heads ({h}); use "
            "ring_attention otherwise")
    rep = h // kv_h

    def local_fn(q_blk, k_blk, v_blk):
        # (B, H, T_local, D) -> (B, H/S, T, D): scatter heads, gather seq
        def a2a_fwd(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)
        qh = a2a_fwd(q_blk)
        kh, vh = a2a_fwd(k_blk), a2a_fwd(v_blk)
        if rep > 1:   # GQA repeat after the wire hop (kv_h/S -> H/S heads)
            kh = jnp.repeat(kh, rep, axis=1)
            vh = jnp.repeat(vh, rep, axis=1)
        out, _ = _block_attn(qh, kh, vh, causal, scale)
        # back: scatter seq, gather heads
        return lax.all_to_all(out.astype(q_blk.dtype), axis_name,
                              split_axis=2, concat_axis=1, tiled=True)

    return _shard_mapped_qkv(local_fn, q, k, v, mesh, axis_name)


def ulysses_sequence_parallel_attention(q, k, v, mesh=None, axis_name="sp",
                                        causal=True, scale=None):
    """NDArray-level wrapper mirroring sequence_parallel_attention."""
    from ..ndarray.ndarray import apply_nary
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if mesh is None or axis_name not in mesh.shape:
        raise MXNetError("ulysses_sequence_parallel_attention needs an "
                         f"ambient mesh with a '{axis_name}' axis")

    def fn(qa, ka, va):
        return ulysses_attention(qa, ka, va, mesh, axis_name, causal, scale)
    return apply_nary(fn, [q, k, v], name="ulysses_attention")
