"""Backward-overlapped gradient communication (ISSUE 5 tentpole).

PR 3 made the data-parallel gradient sync bucketed (parallel/zero.py),
but every bucket's collective still launched only after the WHOLE
backward finished — the serialization that "Exploring the limits of
Concurrency in ML Training on Google TPUs" (arXiv:2011.03641) and the
MLPerf TPU-v3 pod paper (arXiv:1909.09756) identify as the dominant
scaling loss.  Both fix it the same way: start summing each gradient
bucket the moment its gradients are ready, so communication rides under
the remaining backprop compute.

Two halves, one per training path:

- **eager** (``gluon.Trainer``): :class:`OverlapScheduler` here.  It
  registers autograd grad-ready hooks (``_tape.register_grad_ready_hook``
  — they fire in backward order) on every parameter, groups parameters
  into backward-ordered buckets (``zero.BucketPlan(fill_order=...)``
  built from the ORDER OBSERVED on the first backward), and dispatches
  one bucketed ``kvstore.pushpull`` per bucket as soon as the bucket's
  last gradient lands — while backprop is still running.  Dispatch is
  async (jax eager dispatch does not block); ``finish()`` — called from
  ``trainer.step`` — only waits on the tail bucket.
- **in-graph** (``parallel.DataParallelTrainer``): the traced ZeRO-1
  step already makes each bucket's ``reduce_scatter_bucket`` data-
  dependent only on that bucket's own gradients; the scheduler's job
  there is done by the backward-ordered ``BucketPlan`` (buckets complete
  early-to-late during the XLA backward) plus XLA's latency-hiding
  scheduler (``runtime.lhs_flags()`` / ``MXTPU_LHS=1``), which is free
  to hoist each collective under the remaining backward compute.

``MXTPU_OVERLAP_COMM=0`` is the kill switch for both halves: bucket
plans revert to declaration order and the scheduler stands down, which
reproduces the PR 3 monolithic-sync graphs bitwise.
"""
from __future__ import annotations

import time

from ..base import MXNetError
from . import zero as _zero

__all__ = ["OverlapScheduler"]


class OverlapScheduler:
    """Dispatch per-bucket gradient communication from grad-ready hooks.

    ``params`` is the trainer's parameter list; ``keys[i]`` is the
    kvstore key of ``params[i]`` (defaults to the list position, the
    ``gluon.Trainer`` convention).  ``n_accum > 1`` supports gradient
    accumulation: hooks count backward passes per parameter and only
    the final microbatch of each cycle dispatches communication — the
    intermediate backwards accumulate locally for free.

    Lifecycle per optimization cycle::

        install()                      # once, after net.initialize()
        for micro in range(n_accum):
            loss.backward()            # hooks fire; ready buckets launch
        scheduler.finish()             # trainer.step calls this: launch
                                       # stragglers, wait on tail bucket

    The first cycle observes the hook firing order (the true backward
    order of THIS model) and builds the backward-ordered
    ``zero.BucketPlan`` from it; that first cycle therefore dispatches
    monolithically from ``finish()``.  Every later cycle launches
    bucket-by-bucket from inside backward.

    Without a multi-worker kvstore there is nothing to reduce; the
    scheduler still runs its bookkeeping and profiler spans
    (``overlap.bucket_ready`` / ``overlap.bucket_launch`` /
    ``overlap.tail_wait``) so the overlap is observable anywhere.
    """

    def __init__(self, params, kvstore=None, n_accum=1, bound_bytes=None):
        if n_accum < 1:
            raise MXNetError("OverlapScheduler: n_accum must be >= 1")
        self._all_params = list(params)
        self._all_keys = list(range(len(self._all_params)))
        self._kvstore = kvstore
        self._n_accum = int(n_accum)
        self._bound = bound_bytes
        # active set: grad-carrying, initialized params
        self._idxs = [i for i, p in enumerate(self._all_params)
                      if getattr(p, "grad_req", "write") != "null"
                      and getattr(p, "_data", None) is not None]
        self._handles = []
        self._fired = {i: 0 for i in self._idxs}
        self._observed = []            # first-cycle backward order
        self._observed_set = set()
        self._plan = None              # zero.BucketPlan over active idxs
        self._pos = {}                 # param idx -> position in plan
        self._param_bucket = {}        # param idx -> bucket id
        self._remaining = []           # per bucket: set of pending idxs
        self._launched = set()
        self._tail = None              # last launched bucket's grads

    # -- plan -----------------------------------------------------------
    @property
    def plan(self):
        return self._plan

    def _build_plan(self):
        """Backward-ordered bucket assignment from the OBSERVED firing
        order (reverse-topological fill); params that never fired this
        cycle (e.g. frozen branches) append in declaration order."""
        ready = list(self._observed)
        ready += [i for i in self._idxs if i not in self._observed_set]
        self._pos = {i: k for k, i in enumerate(ready)}
        shapes = [self._all_params[i].shape for i in ready]
        # fill_order=None: `ready` IS already the fill order of `shapes`
        self._plan = _zero.BucketPlan(
            shapes, dp=1,
            bound_bytes=self._bound if self._bound is not None
            else _zero.bucket_bound_bytes())
        self._order = ready
        self._param_bucket = {}
        for b, idxs in enumerate(self._plan.buckets):
            for k in idxs:
                self._param_bucket[ready[k]] = b
        self._reset_cycle()

    def _reset_cycle(self):
        self._remaining = [set(self._order[k] for k in idxs)
                           for idxs in self._plan.buckets]
        self._launched = set()

    # -- hooks ----------------------------------------------------------
    def install(self):
        """Register grad-ready hooks on every active parameter."""
        if self._handles:
            return self
        from .. import _tape
        for i in self._idxs:
            arr = self._all_params[i]._data
            self._handles.append(_tape.register_grad_ready_hook(
                arr, self._make_hook(i)))
        return self

    def remove(self):
        for h in self._handles:
            h.remove()
        self._handles = []

    def reset_plan(self):
        """Forget the observed backward order and the bucket plan (the
        elastic-reshard hook: after a world-size change the kvstore ring
        and the profitable bucket layout both changed).  The next cycle
        re-observes and dispatches monolithically from ``finish()``,
        exactly like the first cycle after ``install()``."""
        self._plan = None
        self._observed = []
        self._observed_set = set()
        self._param_bucket = {}
        self._remaining = []
        self._launched = set()
        self._tail = None
        self._fired = {i: 0 for i in self._idxs}

    def _make_hook(self, i):
        def hook(arr):
            self._on_ready(i)
        return hook

    def _on_ready(self, i):
        self._fired[i] = self._fired.get(i, 0) + 1
        if self._fired[i] % self._n_accum != 0:
            return                  # mid-accumulation: local add only
        if self._plan is None:
            if i not in self._observed_set:
                self._observed_set.add(i)
                self._observed.append(i)
            return                  # first cycle: order discovery
        b = self._param_bucket.get(i)
        if b is None or b in self._launched:
            return
        rem = self._remaining[b]
        rem.discard(i)
        if not rem:
            now = time.perf_counter()
            _span(f"overlap.bucket_ready.{b}", now, now)
            self._launch(b)

    # -- dispatch -------------------------------------------------------
    def _launch(self, b):
        """One bucketed communication round for bucket ``b`` — async
        dispatch; nothing here blocks on the wire."""
        from ..ndarray import sparse as _sp
        self._launched.add(b)
        keys, grads, params = [], [], []
        for k in self._plan.buckets[b]:
            i = self._order[k]
            p = self._all_params[i]
            d = p._data
            if d is None or d._grad is None or d._grad_reduced:
                continue
            g = p.grad()
            if isinstance(g, _sp.RowSparseNDArray):
                continue    # row_sparse rides the batched kvstore path
            keys.append(self._all_keys[i])
            grads.append(g)
            params.append(p)
        if not keys:
            return
        t0 = time.perf_counter()
        kv = self._kvstore
        if kv is not None and getattr(kv, "num_workers", 1) > 1:
            kv.pushpull(keys, grads, out=grads)
            for p, g in zip(params, grads):
                p._data._grad = g.data
                p._data._grad_reduced = True
        self._tail = grads
        _span(f"overlap.bucket_launch.{b}", t0, time.perf_counter())

    def finish(self):
        """Called from ``trainer.step``: complete the cycle.  Launches
        any bucket that has not gone out yet (first cycle: all of them,
        monolithically) and waits ONLY on the tail bucket — earlier
        buckets were dispatched during backward and their results are
        ordered before the tail by the runtime."""
        if self._plan is None:
            if not self._observed and not self._idxs:
                return
            self._build_plan()
        for b in self._plan.ready_order:
            if b not in self._launched:
                self._launch(b)
        if self._tail is not None:
            import jax
            t0 = time.perf_counter()
            jax.block_until_ready([g.data for g in self._tail])
            _span("overlap.tail_wait", t0, time.perf_counter())
            self._tail = None
        self._reset_cycle()


def _span(name, t0, t1):
    from .. import profiler
    from ..telemetry import tracing
    profiler.record_span(name, t0, t1)
    if tracing.enabled():
        # the comm spans nest under whatever step/backward span is
        # ambient on the dispatching thread (ISSUE 14): bucket launches
        # that fire during backward show up INSIDE the step timeline
        tracing.record(name, t0, t1)
