"""Tensor parallelism: megatron-style sharding annotations for Gluon layers.

NEW capability vs the reference (SURVEY.md §2.5 TP row: "absent — jit +
NamedSharding on weight matrices"). A Parameter carries a PartitionSpec
(`param.shard(P('tp', None))`); DataParallelTrainer honors it, and XLA
partitions the matmuls over the 'tp' axis with all-gather/reduce-scatter
inserted from the sharding algebra (the scaling-book recipe: annotate, let
XLA place collectives on ICI).

Column-parallel then row-parallel Dense pairs avoid any resharding between
them (activations stay 'tp'-sharded on the hidden axis).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["tp_spec_for_param", "shard_params_tp", "ParallelDense",
           "ParallelEmbedding"]


def tp_spec_for_param(name, shape, kind="auto"):
    """Heuristic megatron specs: weights (out, in):
    column-parallel -> P('tp', None); row-parallel -> P(None, 'tp');
    embeddings (vocab, hidden) -> P(None, 'tp'); 1-D params replicated."""
    if len(shape) < 2:
        return P()
    if kind == "column":
        return P("tp", None)
    if kind == "row":
        return P(None, "tp")
    if "embed" in name:
        return P(None, "tp")
    return P("tp", None)


def shard_params_tp(block, rules=None):
    """Annotate all params of a block with TP specs.

    ``rules``: list of (substring, PartitionSpec); first match wins; default
    heuristic otherwise. Returns the block for chaining."""
    for name, p in block.collect_params().items():
        spec = None
        for pat, s in (rules or []):
            if pat in name:
                spec = s
                break
        if spec is None:
            spec = tp_spec_for_param(name, p.shape or ())
        p.shard(spec)
    return block


class ParallelDense(nn.Dense):
    """Dense with an explicit TP flavor ('column' shards output features,
    'row' shards input features)."""

    def __init__(self, units, parallel_mode="column", **kwargs):
        super().__init__(units, **kwargs)
        if parallel_mode not in ("column", "row"):
            raise MXNetError("parallel_mode must be 'column' or 'row'")
        self.weight.shard(P("tp", None) if parallel_mode == "column"
                          else P(None, "tp"))
        if self.bias is not None:
            self.bias.shard(P("tp") if parallel_mode == "column" else P())


class ParallelEmbedding(nn.Embedding):
    """Embedding sharded over the hidden axis."""

    def __init__(self, input_dim, output_dim, **kwargs):
        super().__init__(input_dim, output_dim, **kwargs)
        self.weight.shard(P(None, "tp"))
