"""Tensor parallelism: megatron-style sharding annotations for Gluon layers.

NEW capability vs the reference (SURVEY.md §2.5 TP row: "absent — jit +
NamedSharding on weight matrices"). A Parameter carries a PartitionSpec
(`param.shard(P('tp', None))`); DataParallelTrainer honors it, and XLA
partitions the matmuls over the 'tp' axis with all-gather/reduce-scatter
inserted from the sharding algebra (the scaling-book recipe: annotate, let
XLA place collectives on ICI).

Column-parallel then row-parallel Dense pairs avoid any resharding between
them (activations stay 'tp'-sharded on the hidden axis).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from .mesh import AXIS_TP

__all__ = ["tp_spec_for_param", "shard_params_tp", "ParallelDense",
           "ParallelEmbedding", "llama_tp_rules", "bert_tp_rules",
           "llama_engine_specs", "shard_model_tp"]


def tp_spec_for_param(name, shape, kind="auto"):
    """Heuristic megatron specs: weights (out, in):
    column-parallel -> P('tp', None); row-parallel -> P(None, 'tp');
    embeddings (vocab, hidden) -> P(None, 'tp'); 1-D params replicated."""
    if len(shape) < 2:
        return P()
    if kind == "column":
        return P(AXIS_TP, None)
    if kind == "row":
        return P(None, AXIS_TP)
    if "embed" in name:
        return P(None, AXIS_TP)
    return P(AXIS_TP, None)


def shard_params_tp(block, rules=None):
    """Annotate all params of a block with TP specs.

    ``rules``: list of (substring, PartitionSpec); first match wins; default
    heuristic otherwise. Returns the block for chaining."""
    for name, p in block.collect_params().items():
        spec = None
        for pat, s in (rules or []):
            if pat in name:
                spec = s
                break
        if spec is None:
            spec = tp_spec_for_param(name, p.shape or ())
        p.shard(spec)
    return block


class ParallelDense(nn.Dense):
    """Dense with an explicit TP flavor ('column' shards output features,
    'row' shards input features)."""

    def __init__(self, units, parallel_mode="column", **kwargs):
        super().__init__(units, **kwargs)
        if parallel_mode not in ("column", "row"):
            raise MXNetError("parallel_mode must be 'column' or 'row'")
        self.weight.shard(P(AXIS_TP, None) if parallel_mode == "column"
                          else P(None, AXIS_TP))
        if self.bias is not None:
            self.bias.shard(P(AXIS_TP) if parallel_mode == "column"
                            else P())


class ParallelEmbedding(nn.Embedding):
    """Embedding sharded over the hidden axis."""

    def __init__(self, input_dim, output_dim, **kwargs):
        super().__init__(input_dim, output_dim, **kwargs)
        self.weight.shard(P(None, AXIS_TP))


# ---------------------------------------------------------------------------
# Model-zoo wiring (ISSUE 11): the megatron rule tables for the llama and
# BERT blocks, keyed on the zoo's parameter names.  Column-parallel
# projections write into the head/hidden axis that the paired
# row-parallel projection immediately consumes, so activations stay
# 'tp'-sharded between them and XLA's sharding algebra inserts exactly
# one reduce per pair (the megatron layout).
# ---------------------------------------------------------------------------

def llama_tp_rules():
    """child-attribute-name -> spec for the llama decoder blocks:
    q/k/v + SwiGLU gate/up column-parallel, o_proj/down_proj
    row-parallel, norms/embeddings replicated (the megatron pairing:
    exactly one reduce per attention/MLP block)."""
    col, row = P(AXIS_TP, None), P(None, AXIS_TP)
    return {"q_proj": col, "k_proj": col, "v_proj": col,
            "gate_proj": col, "up_proj": col,
            "o_proj": row, "down_proj": row}


def llama_engine_specs():
    """The :func:`llama_tp_rules` table re-keyed on the serving
    engine's extracted-weight names (ISSUE 18 sharded serving):
    ``InferenceEngine._extract_weights`` flattens each decoder layer to
    ``{q, k, v, o, gate, up, down}`` projection dicts, and this is the
    one spec source both the structural sharder and the engine's
    at-rest ``device_put`` placement read — the megatron layout cannot
    drift between training and serving."""
    rules = llama_tp_rules()
    return {"q": rules["q_proj"], "k": rules["k_proj"],
            "v": rules["v_proj"], "o": rules["o_proj"],
            "gate": rules["gate_proj"], "up": rules["up_proj"],
            "down": rules["down_proj"]}


def bert_tp_rules():
    """child-attribute-name -> spec for the BERT encoder blocks:
    attention query/key/value + ffn_1 column-parallel, attention out +
    ffn_2 row-parallel."""
    col, row = P(AXIS_TP, None), P(None, AXIS_TP)
    return {"proj_query": col, "proj_key": col, "proj_value": col,
            "ffn_1": col, "proj_out": row, "ffn_2": row}


def shard_model_tp(block, arch):
    """Annotate a model-zoo block for tensor parallelism over the
    MeshConfig 'tp' axis: ``arch`` is ``"llama"`` or ``"bert"``.

    The walk keys on child-block ATTRIBUTE names (``_children`` keys:
    ``q_proj``, ``proj_query``, ``ffn_1``, ...) rather than parameter
    name substrings — the zoo's Dense layers are auto-named
    (``dense0_weight``), so structure, not names, identifies the
    megatron roles.  Column-parallel biases shard with their output
    features; row-parallel biases replicate (added once after the
    reduce).  Returns the block; training through
    ``DataParallelTrainer`` on a mesh with a tp axis then partitions
    every annotated matmul (the trainer honors
    ``Parameter.shard_spec``)."""
    table = {"llama": llama_tp_rules, "bert": bert_tp_rules}.get(arch)
    if table is None:
        raise MXNetError(f"shard_model_tp: unknown arch {arch!r} "
                         f"(llama|bert)")
    rules = table()
    col = P(AXIS_TP, None)

    def walk(b):
        for name, child in getattr(b, "_children", {}).items():
            spec = rules.get(name)
            if spec is not None and hasattr(child, "weight"):
                child.weight.shard(spec)
                if getattr(child, "bias", None) is not None:
                    child.bias.shard(P(AXIS_TP) if spec == col else P())
            walk(child)
    walk(block)
    return block
