"""jax version compatibility for the parallel package.

``shard_map`` moved from ``jax.experimental.shard_map`` to top-level
``jax.shard_map`` (and its ``check_rep`` kwarg became ``check_vma``)
across jax releases; the rest of this package targets the new surface.
This shim lets the package import and run on both, so a jax downgrade
in the base image doesn't take out ``import mxnet_tpu`` (parallel is
imported from the package root).
"""
from __future__ import annotations

try:                                    # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map
    _NEEDS_KWARG_SHIM = False
except ImportError:                     # jax 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEEDS_KWARG_SHIM = True

__all__ = ["shard_map"]


def shard_map(f, *, check_vma=None, **kwargs):
    if check_vma is not None:
        kwargs["check_vma" if not _NEEDS_KWARG_SHIM
               else "check_rep"] = check_vma
    return _shard_map(f, **kwargs)
