"""Fused data-parallel training: one jitted step, grads reduced in-graph.

This is the TPU replacement for the reference's hot loop
(SURVEY.md §3.2 TPU mapping): `record -> forward -> backward ->
kvstore.push/pull -> optimizer.update` becomes ONE jit(train_step) with
donated params/optimizer state. The batch is sharded over the mesh 'dp'
axis; parameters are replicated (or tp-sharded via their Parameter.shard
spec); XLA inserts the gradient all-reduce over ICI automatically from the
sharding algebra — no NCCL, no push/pull (SURVEY.md §2.6).
"""
from __future__ import annotations

import math

import numpy as _np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray import random as _rnd
from .. import _tape
from ..gluon.parameter import _bind_params
from .mesh import current_mesh, make_mesh

__all__ = ["DataParallelTrainer", "all_reduce_gradients"]


# ----------------------------------------------------------------------
# pure optimizer rules (functional mirrors of mx.optimizer kernels)
# ----------------------------------------------------------------------

def _sgd_rule(momentum=0.0, wd=0.0, clip_gradient=None):
    def init(p):
        return {"mom": jnp.zeros_like(p)} if momentum else {}

    def apply(p, g, s, lr):
        if clip_gradient:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        g = g + wd * p
        if momentum:
            m = momentum * s["mom"] - lr * g
            return p + m, {"mom": m}
        return p - lr * g, {}
    return init, apply


def _adam_rule(beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
               clip_gradient=None):
    def init(p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "t": jnp.zeros((), jnp.int32)}

    def apply(p, g, s, lr):
        if clip_gradient:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        g = g + wd * p
        t = s["t"] + 1
        m = beta1 * s["m"] + (1 - beta1) * g
        v = beta2 * s["v"] + (1 - beta2) * jnp.square(g)
        lr_t = lr * jnp.sqrt(1 - beta2 ** t.astype(p.dtype)) / \
            (1 - beta1 ** t.astype(p.dtype))
        return p - lr_t * m / (jnp.sqrt(v) + epsilon), \
            {"m": m, "v": v, "t": t}
    return init, apply


def _lamb_rule(beta1=0.9, beta2=0.999, epsilon=1e-6, wd=0.0,
               clip_gradient=None):
    def init(p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "t": jnp.zeros((), jnp.int32)}

    def apply(p, g, s, lr):
        if clip_gradient:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        t = s["t"] + 1
        m = beta1 * s["m"] + (1 - beta1) * g
        v = beta2 * s["v"] + (1 - beta2) * jnp.square(g)
        m_hat = m / (1 - beta1 ** t.astype(p.dtype))
        v_hat = v / (1 - beta2 ** t.astype(p.dtype))
        update = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * p
        w_norm = jnp.linalg.norm(p)
        u_norm = jnp.linalg.norm(update)
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p - lr * ratio * update, {"m": m, "v": v, "t": t}
    return init, apply


_RULES = {"sgd": _sgd_rule, "nag": _sgd_rule, "adam": _adam_rule,
          "adamw": _adam_rule, "lamb": _lamb_rule}


class DataParallelTrainer:
    """jit(train_step) over a mesh; drop-in upgrade from gluon.Trainer.

    Usage::

        mesh = parallel.make_mesh({'dp': -1})
        trainer = parallel.DataParallelTrainer(net, loss_fn, 'sgd',
            {'learning_rate': 0.1, 'momentum': 0.9}, mesh=mesh)
        loss = trainer.step(data, label)          # one fused jitted step

    The forward/backward/reduce/update all execute as a single XLA program
    with donated buffers (static_alloc/static_shape analog).
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_axis=0, dtype=None, donate=True):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh or current_mesh() or make_mesh({"dp": -1})
        self.batch_axis = batch_axis
        params_kwargs = dict(optimizer_params or {})
        self._lr = params_kwargs.pop("learning_rate", 0.01)
        self._lr_scheduler = params_kwargs.pop("lr_scheduler", None)
        name = optimizer.lower() if isinstance(optimizer, str) else "sgd"
        if name not in _RULES:
            raise MXNetError(
                f"DataParallelTrainer supports {sorted(_RULES)}; for "
                f"'{optimizer}' use gluon.Trainer (eager path)")
        self._rule_init, self._rule_apply = _RULES[name](**params_kwargs)
        self._param_objs = None
        self._opt_state = None
        self._jitted = None
        self._num_update = 0
        self._donate = donate

    # -- parameter plumbing --------------------------------------------
    def _collect(self, *args):
        if self._param_objs is None:
            if any(p._data is None
                   for p in self.block.collect_params().values()):
                # resolve deferred shapes with one eager forward
                with _tape.trace_scope():
                    self.block.forward(*args)
            items = sorted(self.block.collect_params().items())
            self._param_objs = [p for _, p in items]
        return self._param_objs

    def _param_sharding(self, p):
        if p.shard_spec is not None:
            return NamedSharding(self.mesh, p.shard_spec)
        return NamedSharding(self.mesh, P())

    def _build(self):
        block = self.block
        loss_fn = self.loss_fn
        rule_apply = self._rule_apply
        batch_axis = self.batch_axis
        params = self._param_objs

        def train_step(param_vals, opt_state, lr, key, *batch):
            def loss_of(pv):
                prev = _tape.set_training(True)
                binding = {p: NDArray(v) for p, v in zip(params, pv)}
                try:
                    with _tape.trace_scope(), _bind_params(binding), \
                            _rnd.trace_key_scope(key):
                        inputs = [NDArray(b) for b in batch[:-1]]
                        label = NDArray(batch[-1])
                        out = block.forward(*inputs)
                        loss = loss_fn(out, label)
                finally:
                    _tape.set_training(prev)
                return jnp.mean(loss.data)

            loss, grads = jax.value_and_grad(loss_of)(list(param_vals))
            new_params, new_state = [], []
            for p, g, s in zip(param_vals, grads, opt_state):
                np_, ns = rule_apply(p, g.astype(p.dtype), s, lr)
                new_params.append(np_)
                new_state.append(ns)
            return new_params, new_state, loss

        donate = (0, 1) if self._donate else ()
        self._jitted = jax.jit(train_step, donate_argnums=donate)

    # -- public API -----------------------------------------------------
    @property
    def learning_rate(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler(self._num_update)
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = lr

    def step(self, *batch):
        """batch = (*inputs, label) NDArrays. Returns the scalar loss
        NDArray."""
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        params = self._collect(*[NDArray(b) for b in inputs[:-1]])
        mesh = self.mesh
        inputs = [jax.device_put(b, NamedSharding(
            mesh, P(*([None] * self.batch_axis + (["dp"] if b.ndim else [])))))
            for b in inputs]
        param_vals = [jax.device_put(p.data().data, self._param_sharding(p))
                      for p in params]
        if self._opt_state is None:
            self._opt_state = [
                jax.tree.map(lambda x: jax.device_put(
                    x, NamedSharding(mesh, P())), self._rule_init(v))
                for v in param_vals]
        if self._jitted is None:
            self._build()
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = self._jitted(
            param_vals, self._opt_state, lr, key, *inputs)
        self._num_update += 1
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        return NDArray(loss)


def all_reduce_gradients(params, mesh=None, axis="dp"):
    """Eager helper: sum .grad across worker *processes* for parameters
    trained outside the fused step (reference: trainer._allreduce_grads).

    Within one process an eagerly computed gradient already covers the full
    local batch, so there is nothing to reduce; across processes this is a
    real all-reduce via multihost allgather+sum (the out-of-graph KVStore
    path — SURVEY.md §7 "in-graph collectives vs push/pull API" perf cliff).
    """
    if jax.process_count() == 1:
        return params
    from jax.experimental import multihost_utils
    for p in params:
        if getattr(p, "_data", None) is not None and \
                p._data._grad is not None:
            stacked = multihost_utils.process_allgather(p._data._grad)
            p._data._grad = jnp.sum(stacked, axis=0)
    return params
