"""Fused data-parallel training: one jitted step, grads reduced in-graph.

This is the TPU replacement for the reference's hot loop
(SURVEY.md §3.2 TPU mapping): `record -> forward -> backward ->
kvstore.push/pull -> optimizer.update` becomes ONE jit(train_step) with
donated params/optimizer state. The batch is sharded over the mesh 'dp'
axis; parameters are replicated (or tp-sharded via their Parameter.shard
spec); XLA inserts the gradient all-reduce over ICI automatically from the
sharding algebra — no NCCL, no push/pull (SURVEY.md §2.6).
"""
from __future__ import annotations

import math

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray import random as _rnd
from .. import _tape
from ..gluon.parameter import _bind_params
from .mesh import current_mesh, make_mesh

__all__ = ["DataParallelTrainer", "all_reduce_gradients"]


# The update math lives in ONE place — mx.optimizer's functional kernels
# (optimizer.fused_rule); the eager Optimizer.update path delegates to the
# same kernels, so fused and eager training can never diverge (VERDICT r1
# #6: the old local copies silently mapped NAG->SGD and AdamW->Adam).
from ..optimizer.optimizer import fused_rule, _FUSED_KERNELS

_RULES = _FUSED_KERNELS  # names the fused path accepts


class DataParallelTrainer:
    """jit(train_step) over a mesh; drop-in upgrade from gluon.Trainer.

    Usage::

        mesh = parallel.make_mesh({'dp': -1})
        trainer = parallel.DataParallelTrainer(net, loss_fn, 'sgd',
            {'learning_rate': 0.1, 'momentum': 0.9}, mesh=mesh)
        loss = trainer.step(data, label)          # one fused jitted step

    The forward/backward/reduce/update all execute as a single XLA program
    with donated buffers (static_alloc/static_shape analog).
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_axis=0, dtype=None, donate=True,
                 shard_updates=False, label_batch_axis=None):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh or current_mesh() or make_mesh({"dp": -1})
        self.batch_axis = batch_axis
        self._label_bax = (batch_axis if label_batch_axis is None
                           else label_batch_axis)
        # ZeRO-1 / "weight update sharding" (MLPerf-on-TPU-pods technique,
        # PAPERS.md arXiv:1909.09756 / arXiv:2011.03641): shard the
        # optimizer state and the update over 'dp' via sharding
        # constraints, so XLA lowers the gradient all-reduce into
        # reduce-scatter + (post-update) all-gather — identical wire
        # bytes (ring AR == RS+AG), 1/N optimizer memory and update
        # compute per chip
        self._shard_updates = bool(shard_updates) and \
            self.mesh.shape.get("dp", 1) > 1
        self._ws_eligible = None
        params_kwargs = dict(optimizer_params or {})
        self._lr = params_kwargs.pop("learning_rate", 0.01)
        self._lr_scheduler = params_kwargs.pop("lr_scheduler", None)
        wd = params_kwargs.pop("wd", 0.0)
        clip = params_kwargs.pop("clip_gradient", None)
        name = optimizer.lower() if isinstance(optimizer, str) else "sgd"
        if name not in _RULES:
            raise MXNetError(
                f"DataParallelTrainer supports {sorted(_RULES)}; for "
                f"'{optimizer}' use gluon.Trainer (eager path)")
        self._rule_init, _kernel_apply = fused_rule(
            name, clip_gradient=clip, **params_kwargs)
        self._rule_apply = lambda p, g, s, lr: _kernel_apply(p, g, s, lr, wd)
        self._param_objs = None
        self._param_vals = None   # device-resident, sharded; owned by us
        self._opt_state = None
        self._jitted = None
        self._jitted_indexed = None
        self._jit_accum_cache = {}
        self._num_update = 0
        self._donate = donate

    # -- parameter plumbing --------------------------------------------
    def _collect(self, *args):
        if self._param_objs is None:
            if any(p._data is None
                   for p in self.block.collect_params().values()):
                # resolve deferred shapes with one eager forward
                with _tape.trace_scope():
                    self.block.forward(*args)
            items = sorted(self.block.collect_params().items())
            self._param_objs = [p for _, p in items]
        return self._param_objs

    def _param_sharding(self, p):
        if p.shard_spec is not None:
            return NamedSharding(self.mesh, p.shard_spec)
        return NamedSharding(self.mesh, P())

    # -- weight-update sharding helpers ---------------------------------
    def _ws_flags(self, param_vals):
        """Which params take the sharded update: replicated params whose
        leading dim divides the dp axis (tp-sharded params keep their own
        spec; oddly-shaped leftovers stay replicated — correct either
        way, this is a memory/compute optimization, not semantics)."""
        if self._ws_eligible is None:
            dp = self.mesh.shape.get("dp", 1)
            self._ws_eligible = [
                self._shard_updates and p.shard_spec is None and
                v.ndim >= 1 and v.shape[0] % dp == 0 and v.shape[0] >= dp
                for p, v in zip(self._param_objs, param_vals)]
        return self._ws_eligible

    def _ws_spec(self, leaf_ndim):
        return NamedSharding(self.mesh,
                             P(*(["dp"] + [None] * (leaf_ndim - 1))))

    def _ws_leaf_sharding(self, x, ref_dim0):
        """The ONE predicate for how a state leaf lives under weight-update
        sharding: per-element leaves (same leading dim as the param) are
        dp-sharded, scalar leaves (step counters) replicated.  Shared by
        the initial device_put and the traced constraints so the two can
        never disagree (which would force a reshard every step)."""
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == ref_dim0:
            return self._ws_spec(x.ndim)
        return NamedSharding(self.mesh, P())

    def _ws_constrain_state(self, s, ref_dim0):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, self._ws_leaf_sharding(x, ref_dim0)), s)

    def _eff_bax(self, ndim, is_label=False):
        """Effective batch axis for an array of the given rank.

        Inputs carry the batch on ``batch_axis``; the label carries it
        on ``label_batch_axis`` (defaults to batch_axis).  Rank-1 arrays
        are per-sample vectors whatever the nominal axis (classic (B,)
        labels under time-major batch_axis=1).  Rank>=2 arrays MUST have
        their batch on the configured axis — that is the API contract; a
        (B, C) soft-label under time-major data needs
        ``label_batch_axis=0``, it cannot be inferred from shape."""
        ax = self._label_bax if is_label else self.batch_axis
        if ndim <= 1:
            return 0
        if ax >= ndim:
            raise MXNetError(
                f"batch axis {ax} out of range for rank-{ndim} array")
        return ax

    def _batch_sharding(self, b, is_label=False):
        if not b.ndim:
            return NamedSharding(self.mesh, P())
        ax = self._eff_bax(b.ndim, is_label)
        spec = [None] * b.ndim
        spec[ax] = "dp"
        return NamedSharding(self.mesh, P(*spec))

    def _put_batch(self, inputs):
        """device_put every batch array with its batch sharding; the
        LAST array is the label (single convention for step/step_accum)."""
        return [jax.device_put(b, self._batch_sharding(
            b, is_label=(i == len(inputs) - 1)))
            for i, b in enumerate(inputs)]

    def _make_loss_of(self):
        """The traced fwd+loss closure — ONE source for every step
        variant (plain, indexed, accumulating)."""
        block = self.block
        loss_fn = self.loss_fn
        params = self._param_objs

        def loss_of(pv, key, inputs, label):
            prev = _tape.set_training(True)
            binding = {p: NDArray(v) for p, v in zip(params, pv)}
            try:
                with _tape.trace_scope(), _bind_params(binding), \
                        _rnd.trace_key_scope(key):
                    out = block.forward(*[NDArray(b) for b in inputs])
                    loss = loss_fn(out, NDArray(label))
            finally:
                _tape.set_training(prev)
            return jnp.mean(loss.data)
        return loss_of

    def _apply_updates(self, param_vals, grads, opt_state, lr):
        """The optimizer update incl. ZeRO-1 sharding constraints — ONE
        source for every step variant (VERDICT r1 #6: duplicated update
        loops silently diverged once; never again)."""
        rule_apply = self._rule_apply
        ws = self._ws_flags(param_vals)
        new_params, new_state = [], []
        for p, g, s, shard in zip(param_vals, grads, opt_state, ws):
            g = g.astype(p.dtype)
            if shard:
                # constrain grad + state to 'dp' shards: XLA lowers
                # the grad psum into a reduce-scatter feeding a
                # 1/N-sized update, then the P() constraint below
                # all-gathers the fresh params (ZeRO-1)
                g = jax.lax.with_sharding_constraint(
                    g, self._ws_spec(g.ndim))
                p_sh = jax.lax.with_sharding_constraint(
                    p, self._ws_spec(p.ndim))
                s = self._ws_constrain_state(s, p.shape[0])
                np_, ns = rule_apply(p_sh, g, s, lr)
                np_ = jax.lax.with_sharding_constraint(
                    np_, NamedSharding(self.mesh, P()))
            else:
                np_, ns = rule_apply(p, g, s, lr)
            new_params.append(np_)
            new_state.append(ns)
        return new_params, new_state

    def _step_body(self):
        """The fused fwd/bwd/reduce/update body shared by the *batch and
        indexed-epoch jit entry points (single source — the step paths
        can never diverge)."""
        loss_of = self._make_loss_of()

        def body(param_vals, opt_state, lr, key, inputs, label):
            loss, grads = jax.value_and_grad(loss_of)(
                list(param_vals), key, inputs, label)
            new_params, new_state = self._apply_updates(
                param_vals, grads, opt_state, lr)
            return new_params, new_state, loss
        return body

    def _build(self):
        body = self._step_body()

        def train_step(param_vals, opt_state, lr, key, *batch):
            return body(param_vals, opt_state, lr, key,
                        list(batch[:-1]), batch[-1])

        donate = (0, 1) if self._donate else ()
        self._jitted = jax.jit(train_step, donate_argnums=donate)

    def _build_accum(self, n_micro):
        """Fused step with in-graph gradient accumulation: a ``lax.scan``
        over ``n_micro`` microbatches (one microbatch's activations live
        at a time), f32 grad accumulation, ONE optimizer update on the
        mean grad.  Big-batch training without big-batch activation
        memory — the reference reaches the same regime eagerly via
        grad_req='add' + stepping every N batches (gluon/trainer.py);
        here the whole accumulation compiles into the step.  Loss and
        update logic come from the same _make_loss_of/_apply_updates the
        plain step uses (single source, cannot diverge)."""
        loss_of = self._make_loss_of()

        def split_micro(b, is_label=False):
            # split each array's own effective BATCH axis into n_micro
            # leading scan slices, preserving the layout within each
            # microbatch (rank-1 labels under batch_axis=1 split on
            # axis 0 — see _eff_bax)
            bax = self._eff_bax(b.ndim, is_label)
            s = b.shape
            b = b.reshape(s[:bax] + (n_micro, s[bax] // n_micro)
                          + s[bax + 1:])
            return jnp.moveaxis(b, bax, 0)

        def train_step(param_vals, opt_state, lr, key, *batch):
            inputs, label = list(batch[:-1]), batch[-1]
            micro_in = [split_micro(b) for b in inputs]
            micro_lab = split_micro(label, is_label=True)
            keys = jax.random.split(key, n_micro)

            def scan_step(carry, xs):
                acc, loss_sum = carry
                *mb, lab, k = xs
                loss, grads = jax.value_and_grad(loss_of)(
                    list(param_vals), k, mb, lab)
                acc = [a + g.astype(jnp.float32)
                       for a, g in zip(acc, grads)]
                return (acc, loss_sum + loss), None

            init = ([jnp.zeros(v.shape, jnp.float32) for v in param_vals],
                    jnp.zeros((), jnp.float32))
            (acc, loss_sum), _ = lax.scan(
                scan_step, init, tuple(micro_in) + (micro_lab, keys))
            mean_grads = [g / n_micro for g in acc]
            new_params, new_state = self._apply_updates(
                param_vals, mean_grads, opt_state, lr)
            return new_params, new_state, loss_sum / n_micro

        donate = (0, 1) if self._donate else ()
        return jax.jit(train_step, donate_argnums=donate)

    def step_accum(self, *batch, n_micro):
        """One fused update from ``n_micro`` microbatches: batch arrays
        carry n_micro * B elements on ``batch_axis`` and are consumed
        microbatch-at-a-time inside the compiled step (see
        :meth:`_build_accum`).  Returns the mean microbatch loss."""
        if n_micro < 1:
            raise MXNetError("step_accum: n_micro must be >= 1")
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        bax = self._eff_bax(inputs[-1].ndim, is_label=True)
        if inputs[-1].shape[bax] % n_micro:
            raise MXNetError(
                f"step_accum: batch axis {bax} size "
                f"{inputs[-1].shape[bax]} not divisible by n_micro "
                f"{n_micro}")
        if self._param_objs is None:
            # one-microbatch probe resolves deferred shapes (sliced on
            # each input's own effective batch axis); skipped once
            # params exist
            probe = [NDArray(jnp.take(
                b, jnp.arange(max(1, b.shape[self._eff_bax(b.ndim)]
                                  // n_micro)),
                axis=self._eff_bax(b.ndim))) for b in inputs[:-1]]
            params = self._collect(*probe)
        else:
            params = self._param_objs
        inputs = self._put_batch(inputs)
        self._ensure_device_state(params)
        jitted = self._jit_accum_cache.get(n_micro)
        if jitted is None:
            jitted = self._build_accum(n_micro)
            self._jit_accum_cache[n_micro] = jitted
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = jitted(
            self._param_vals, self._opt_state, lr, key, *inputs)
        self._num_update += 1
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        return NDArray(loss)

    def _build_indexed(self):
        body = self._step_body()

        def train_step(param_vals, opt_state, lr, key, superdata,
                       superlabel, i):
            data = jax.lax.dynamic_index_in_dim(superdata, i, 0,
                                                keepdims=False)
            label_b = jax.lax.dynamic_index_in_dim(superlabel, i, 0,
                                                   keepdims=False)
            return body(param_vals, opt_state, lr, key, [data], label_b)

        donate = (0, 1) if self._donate else ()
        self._jitted_indexed = jax.jit(train_step, donate_argnums=donate)

    # -- public API -----------------------------------------------------
    @property
    def learning_rate(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler(self._num_update)
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = lr

    def step(self, *batch):
        """batch = (*inputs, label) NDArrays. Returns the scalar loss
        NDArray."""
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        params = self._collect(*[NDArray(b) for b in inputs[:-1]])
        inputs = self._put_batch(inputs)
        self._ensure_device_state(params)
        if self._jitted is None:
            self._build()
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = self._jitted(
            self._param_vals, self._opt_state, lr, key, *inputs)
        self._num_update += 1
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        return NDArray(loss)

    def put_epoch(self, superdata, superlabel):
        """Upload an epoch of batches to device once: superdata
        (n_batches, B, ...), superlabel (n_batches, B, ...). Returns an
        opaque handle for :meth:`step_indexed`.

        Device-resident epoch feeding: per step only a scalar index
        crosses host->device; the batch select is an in-graph
        ``dynamic_index``. This is the TPU analog of the reference's
        PrefetcherIter keeping decoded batches pinned
        (src/io/iter_prefetcher.h) — and on remote-tunneled hosts it
        avoids the per-step H2D dispatch stall entirely.
        """
        mesh = self.mesh
        sd = jnp.asarray(superdata.data if isinstance(superdata, NDArray)
                         else superdata)
        sl = jnp.asarray(superlabel.data if isinstance(superlabel, NDArray)
                         else superlabel)
        def epoch_spec(a, is_label=False):
            # leading epoch axis replicated; the within-batch sharding
            # follows the same _eff_bax rule as step()/step_accum()
            if a.ndim < 2:
                raise MXNetError(
                    f"put_epoch expects super-arrays with a leading epoch "
                    f"axis, i.e. (n_batches, batch, ...) with ndim >= 2; "
                    f"got shape {tuple(a.shape)}. Stack per-step batches "
                    f"along a new axis 0 before calling put_epoch.")
            inner = [None] * (a.ndim - 1)
            inner[self._eff_bax(a.ndim - 1, is_label)] = "dp"
            return P(*([None] + inner))

        spec_d = epoch_spec(sd)
        spec_l = epoch_spec(sl, is_label=True)
        # caller owns the handle; dropping it frees the device buffers
        return (jax.device_put(sd, NamedSharding(mesh, spec_d)),
                jax.device_put(sl, NamedSharding(mesh, spec_l)))

    def _ensure_device_state(self, params):
        """Params stay resident on device across steps (VERDICT r1 weak
        #6: re-device_put per step put a host round on the timed path).
        Only a parameter externally mutated since our last write (identity
        check against the cached array) is re-transferred."""
        if self._param_vals is None:
            self._param_vals = [
                jax.device_put(p.data().data, self._param_sharding(p))
                for p in params]
        else:
            for i, p in enumerate(params):
                if p._data is not None and \
                        p._data._data is not self._param_vals[i]:
                    self._param_vals[i] = jax.device_put(
                        p.data().data, self._param_sharding(p))
        if self._opt_state is None:
            ws = self._ws_flags(self._param_vals)
            def put(x, shard, dim0):
                if shard:
                    return jax.device_put(x, self._ws_leaf_sharding(x, dim0))
                return jax.device_put(x, NamedSharding(self.mesh, P()))
            self._opt_state = [
                jax.tree.map(
                    lambda x, s=shard, d=v.shape[0] if v.ndim else 1:
                    put(x, s, d), self._rule_init(v))
                for v, shard in zip(self._param_vals, ws)]

    def step_indexed(self, epoch_handle, i):
        """One fused train step on batch ``i`` of a resident epoch
        (see :meth:`put_epoch`)."""
        superdata, superlabel = epoch_handle
        if self._param_objs is None:
            # probe batch only for deferred-shape resolution on first call
            self._collect(NDArray(superdata[0]))
        params = self._param_objs
        self._ensure_device_state(params)
        if self._jitted_indexed is None:
            self._build_indexed()
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = self._jitted_indexed(
            self._param_vals, self._opt_state, lr, key, superdata,
            superlabel, jnp.asarray(i, jnp.int32))
        self._num_update += 1
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        return NDArray(loss)


def all_reduce_gradients(params, mesh=None, axis="dp"):
    """Eager helper: sum .grad across worker *processes* for parameters
    trained outside the fused step (reference: trainer._allreduce_grads).

    Within one process an eagerly computed gradient already covers the full
    local batch, so there is nothing to reduce; across processes this is a
    real all-reduce via multihost allgather+sum (the out-of-graph KVStore
    path — SURVEY.md §7 "in-graph collectives vs push/pull API" perf cliff).
    """
    if jax.process_count() == 1:
        return params
    from jax.experimental import multihost_utils
    for p in params:
        if getattr(p, "_data", None) is not None and \
                p._data._grad is not None:
            stacked = multihost_utils.process_allgather(p._data._grad)
            p._data._grad = jnp.sum(stacked, axis=0)
    return params
