"""Fused data-parallel training: one jitted step, grads reduced in-graph.

This is the TPU replacement for the reference's hot loop
(SURVEY.md §3.2 TPU mapping): `record -> forward -> backward ->
kvstore.push/pull -> optimizer.update` becomes ONE jit(train_step) with
donated params/optimizer state. The batch is sharded over the mesh 'dp'
axis. Two gradient-sync pipelines exist:

- default: parameters replicated, XLA inserts the gradient all-reduce
  over ICI automatically from the sharding algebra (SURVEY.md §2.6).
- ``shard_updates=True`` (ZeRO-1, ISSUE 3 tentpole): the step runs as a
  ``shard_map`` over 'dp' — per-chip fwd/bwd, gradients flattened into
  size-bounded buckets (``MXTPU_COMM_BUCKET_MB``), an explicit
  reduce-scatter (optionally quantized on the wire via
  ``MXTPU_COMM_DTYPE=bf16|int8``), a 1/N-sized optimizer update against
  bucket-sharded optimizer state, and one all-gather of the fresh
  parameters per bucket.  Same ring wire bytes as all-reduce
  (RS+AG == AR), 1/N optimizer HBM and update compute per chip, and
  few/large collectives instead of one per tensor (parallel/zero.py;
  arXiv:1909.09756 weight-update sharding, arXiv:2506.17615 EQuARX).
  ``MXTPU_SHARDED_SYNC=0`` is the kill switch back to the psum path.
"""
from __future__ import annotations

import math

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..lint import donation as _donation
from ..ndarray.ndarray import NDArray
from ..ndarray import random as _rnd
from .. import _tape
from .. import telemetry as _telem
from ..telemetry import tracing as _trace
from ..telemetry import watchdog as _watchdog
from ..telemetry import costmodel as _costmodel
from ..gluon.parameter import _bind_params
from ._compat import shard_map
from .mesh import (current_mesh, make_mesh, MeshConfig,
                   AXIS_DP, AXIS_TP, AXIS_PP)
from . import zero as _zero

__all__ = ["DataParallelTrainer", "all_reduce_gradients"]


# The update math lives in ONE place — mx.optimizer's functional kernels
# (optimizer.fused_rule); the eager Optimizer.update path delegates to the
# same kernels, so fused and eager training can never diverge (VERDICT r1
# #6: the old local copies silently mapped NAG->SGD and AdamW->Adam).
from ..optimizer.optimizer import fused_rule, _FUSED_KERNELS

_RULES = _FUSED_KERNELS  # names the fused path accepts


class DataParallelTrainer:
    """jit(train_step) over a mesh; drop-in upgrade from gluon.Trainer.

    Usage::

        mesh = parallel.make_mesh({'dp': -1})
        trainer = parallel.DataParallelTrainer(net, loss_fn, 'sgd',
            {'learning_rate': 0.1, 'momentum': 0.9}, mesh=mesh)
        loss = trainer.step(data, label)          # one fused jitted step

    The forward/backward/reduce/update all execute as a single XLA program
    with donated buffers (static_alloc/static_shape analog).
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_axis=0, dtype=None, donate=True,
                 shard_updates=False, label_batch_axis=None,
                 mesh_config=None, pp_microbatches=None):
        self.block = block
        self.loss_fn = loss_fn
        # ONE source of mesh truth (ISSUE 11): an explicit MeshConfig
        # wins, then an explicit/ambient Mesh (config derived from its
        # axis names), then the MXTPU_MESH env spec, then flat dp over
        # all devices — the unset-env default builds exactly the
        # Mesh(('dp',), N) of the flat trainer, bitwise.
        if mesh_config is None and mesh is None:
            mesh = current_mesh()
        if mesh is not None:
            self.mesh = mesh
            self.mesh_config = MeshConfig.for_mesh(mesh)
        else:
            cfg = mesh_config or MeshConfig.from_env() \
                or MeshConfig(dp=-1)
            self.mesh_config = cfg = cfg.resolve(len(jax.devices()))
            self.mesh = cfg.build()
        # pipeline microbatch knob: arg > MXTPU_PP_MICROBATCH env >
        # 2 ticks of work per stage (the smallest schedule with a
        # steady-state 1F1B phase)
        if pp_microbatches is None:
            import os as _os
            pp_microbatches = int(_os.environ.get(
                "MXTPU_PP_MICROBATCH", 2 * self.mesh_config.pp))
        self._pp_microbatches = max(1, int(pp_microbatches))
        self._pp_exec = None          # built on first pp step
        self.batch_axis = batch_axis
        self._label_bax = (batch_axis if label_batch_axis is None
                           else label_batch_axis)
        # ZeRO-1 sharded gradient sync (see module docstring). Resolved
        # lazily in _zero1_active(): needs the optimizer rule (elementwise
        # kernels only) and the parameter shard specs (pure-dp only).
        # The raw request survives separately so rebuild() can re-derive
        # the effective flag for a different world size (dp may cross 1).
        self._shard_requested = bool(shard_updates)
        # ZeRO-1 runs on the pure-dp composition only: tp-sharded
        # params and pp-staged params have their own state layouts
        self._shard_updates = self._shard_requested and \
            self.mesh.shape.get(AXIS_DP, 1) > 1 and \
            self.mesh_config.tp == 1 and self.mesh_config.pp == 1
        self._zero1 = None              # tri-state; resolved lazily
        self._plan = None               # zero.BucketPlan once params known
        self._comm_dtype = _zero.comm_dtype()   # read ONCE at construction
        # backward-overlapped comm (ISSUE 5): read ONCE, like the wire
        # dtype — a mid-training env flip must not re-plan the buckets
        self._overlap_comm = _zero.overlap_comm_enabled()
        params_kwargs = dict(optimizer_params or {})
        self._lr = params_kwargs.pop("learning_rate", 0.01)
        self._lr_scheduler = params_kwargs.pop("lr_scheduler", None)
        wd = params_kwargs.pop("wd", 0.0)
        clip = params_kwargs.pop("clip_gradient", None)
        name = optimizer.lower() if isinstance(optimizer, str) else "sgd"
        if name not in _RULES:
            raise MXNetError(
                f"DataParallelTrainer supports {sorted(_RULES)}; for "
                f"'{optimizer}' use gluon.Trainer (eager path)")
        self._rule_name = name
        self._rule_init, _kernel_apply = fused_rule(
            name, clip_gradient=clip, **params_kwargs)
        self._rule_apply = lambda p, g, s, lr: _kernel_apply(p, g, s, lr, wd)
        # ZeRO-1 flat-shard updates route through the fused bucket rule:
        # on TPU one Pallas kernel walks the whole flat bucket (ISSUE 6);
        # everywhere else it IS the fused_rule kernel (bitwise identical)
        from ..ops.fused_update import fused_bucket_rule
        _, _bucket_kernel = fused_bucket_rule(
            name, clip_gradient=clip, **params_kwargs)
        self._bucket_apply = lambda p, g, s, lr: \
            _bucket_kernel(p, g, s, lr, wd)
        self._param_objs = None
        self._param_vals = None   # device-resident, sharded; owned by us
        self._opt_state = None
        self._jitted = None
        self._jitted_indexed = None
        self._jit_accum_cache = {}
        self._jit_multi_cache = {}
        self._jit_zero1_cache = {}
        self._num_update = 0
        self._donate = donate
        # live MFU accounting (ISSUE 14): per-compiled-step XLA FLOP
        # cost, computed at most once per jitted object and only when
        # the chip peak is known (costmodel.live_cost_enabled)
        self._live_cost = {}         # id(jitted) -> (jitted, flops)
        self._last_step_flops = None
        self._live_peak = ()         # () = not yet resolved
        # memory honesty (ISSUE 15): exact byte gauges for the flight
        # recorder's memory block, published once per build
        self._mem_gauges_stale = True

    # -- parameter plumbing --------------------------------------------
    def _collect(self, *args):
        if self._param_objs is None:
            if any(p._data is None
                   for p in self.block.collect_params().values()):
                # resolve deferred shapes with one eager forward
                with _tape.trace_scope():
                    self.block.forward(*args)
            items = sorted(self.block.collect_params().items())
            self._param_objs = [p for _, p in items]
        return self._param_objs

    def _param_sharding(self, p):
        if p.shard_spec is not None:
            return NamedSharding(self.mesh, p.shard_spec)
        return NamedSharding(self.mesh, P())

    def _eff_bax(self, ndim, is_label=False):
        """Effective batch axis for an array of the given rank.

        Inputs carry the batch on ``batch_axis``; the label carries it
        on ``label_batch_axis`` (defaults to batch_axis).  Rank-1 arrays
        are per-sample vectors whatever the nominal axis (classic (B,)
        labels under time-major batch_axis=1).  Rank>=2 arrays MUST have
        their batch on the configured axis — that is the API contract; a
        (B, C) soft-label under time-major data needs
        ``label_batch_axis=0``, it cannot be inferred from shape."""
        ax = self._label_bax if is_label else self.batch_axis
        if ndim <= 1:
            return 0
        if ax >= ndim:
            raise MXNetError(
                f"batch axis {ax} out of range for rank-{ndim} array")
        return ax

    def _batch_sharding(self, b, is_label=False):
        if not b.ndim:
            return NamedSharding(self.mesh, P())
        ax = self._eff_bax(b.ndim, is_label)
        spec = [None] * b.ndim
        spec[ax] = AXIS_DP
        return NamedSharding(self.mesh, P(*spec))

    def _batch_spec(self, ndim, is_label=False):
        """The PartitionSpec twin of :meth:`_batch_sharding` (shard_map
        in_specs need bare specs, not NamedShardings)."""
        if not ndim:
            return P()
        spec = [None] * ndim
        spec[self._eff_bax(ndim, is_label)] = AXIS_DP
        return P(*spec)

    def _put_batch(self, inputs):
        """device_put every batch array with its batch sharding; the
        LAST array is the label (single convention for step/step_accum)."""
        return [jax.device_put(b, self._batch_sharding(
            b, is_label=(i == len(inputs) - 1)))
            for i, b in enumerate(inputs)]

    def _stacked_spec(self, ndim, is_label=False):
        """PartitionSpec for a K-step stacked batch array (K, batch,
        ...): leading scan axis replicated, within-batch sharding by the
        same ``_eff_bax`` rule as :meth:`_batch_spec`."""
        inner = [None] * (ndim - 1)
        if ndim - 1 >= 1:
            inner[self._eff_bax(ndim - 1, is_label)] = AXIS_DP
        return P(*([None] + inner))

    def _put_stacked(self, steps):
        """Stack K per-step batches along a new leading axis and place
        them on the mesh (one H2D per input position, not one per
        step)."""
        n_in = len(steps[0])
        out = []
        for i in range(n_in):
            stacked = jnp.stack([s[i] for s in steps])
            sharding = NamedSharding(self.mesh, self._stacked_spec(
                stacked.ndim, is_label=(i == n_in - 1)))
            out.append(jax.device_put(stacked, sharding))
        return out

    def _make_loss_of(self):
        """The traced fwd+loss closure — ONE source for every step
        variant (plain, indexed, accumulating), replicated or sharded."""
        block = self.block
        loss_fn = self.loss_fn
        params = self._param_objs

        def loss_of(pv, key, inputs, label):
            prev = _tape.set_training(True)
            binding = {p: NDArray(v) for p, v in zip(params, pv)}
            try:
                with _tape.trace_scope(), _bind_params(binding), \
                        _rnd.trace_key_scope(key):
                    out = block.forward(*[NDArray(b) for b in inputs])
                    loss = loss_fn(out, NDArray(label))
            finally:
                _tape.set_training(prev)
            return jnp.mean(loss.data)
        return loss_of

    def _apply_updates(self, param_vals, grads, opt_state, lr):
        """The replicated optimizer update — ONE source for every
        psum-path step variant (VERDICT r1 #6: duplicated update loops
        silently diverged once; never again).  The ZeRO-1 pipeline has
        its own single source, :meth:`_zero1_sync_update`."""
        rule_apply = self._rule_apply
        new_params, new_state = [], []
        for p, g, s in zip(param_vals, grads, opt_state):
            np_, ns = rule_apply(p, g.astype(p.dtype), s, lr)
            new_params.append(np_)
            new_state.append(ns)
        return new_params, new_state

    def _step_body(self):
        """The fused fwd/bwd/reduce/update body shared by the *batch and
        indexed-epoch jit entry points (single source — the step paths
        can never diverge)."""
        loss_of = self._make_loss_of()

        def body(param_vals, opt_state, lr, key, inputs, label):
            loss, grads = jax.value_and_grad(loss_of)(
                list(param_vals), key, inputs, label)
            new_params, new_state = self._apply_updates(
                param_vals, grads, opt_state, lr)
            return new_params, new_state, loss
        return body

    def _build(self):
        body = self._step_body()

        def train_step(param_vals, opt_state, lr, key, *batch):
            return body(param_vals, opt_state, lr, key,
                        list(batch[:-1]), batch[-1])

        donate = (0, 1) if self._donate else ()
        self._jitted = jax.jit(train_step, donate_argnums=donate)

    def _grad_fn(self, loss_of, n_micro):
        """``(param_vals, key, inputs, label) -> (grads, mean_loss)`` —
        plain gradients or the ``n_micro``-microbatch accumulation scan
        (the step_accum skeleton).  ONE source for the psum, ZeRO-1 and
        multi-step step bodies (they can never diverge)."""
        if n_micro <= 1:
            def plain(param_vals, key, inputs, label):
                loss, grads = jax.value_and_grad(loss_of)(
                    list(param_vals), key, inputs, label)
                return grads, loss
            return plain
        split_micro = self._micro_splitter(n_micro)

        def accum(param_vals, key, inputs, label):
            micro_in = [split_micro(b) for b in inputs]
            micro_lab = split_micro(label, is_label=True)
            keys = jax.random.split(key, n_micro)

            def scan_step(carry, xs):
                acc, loss_sum = carry
                *mb, lab, k = xs
                loss, grads = jax.value_and_grad(loss_of)(
                    list(param_vals), k, mb, lab)
                acc = [a + g.astype(jnp.float32)
                       for a, g in zip(acc, grads)]
                return (acc, loss_sum + loss), None

            init = ([jnp.zeros(v.shape, jnp.float32)
                     for v in param_vals], jnp.zeros((), jnp.float32))
            (acc, loss_sum), _ = lax.scan(
                scan_step, init, tuple(micro_in) + (micro_lab, keys))
            return [g / n_micro for g in acc], loss_sum / n_micro
        return accum

    def _build_accum(self, n_micro):
        """Fused step with in-graph gradient accumulation: a ``lax.scan``
        over ``n_micro`` microbatches (one microbatch's activations live
        at a time), f32 grad accumulation, ONE optimizer update on the
        mean grad.  Big-batch training without big-batch activation
        memory — the reference reaches the same regime eagerly via
        grad_req='add' + stepping every N batches (gluon/trainer.py);
        here the whole accumulation compiles into the step.  Loss and
        update logic come from the same _grad_fn/_apply_updates the
        plain step uses (single source, cannot diverge)."""
        grad_fn = self._grad_fn(self._make_loss_of(), n_micro)

        def train_step(param_vals, opt_state, lr, key, *batch):
            inputs, label = list(batch[:-1]), batch[-1]
            mean_grads, mean_loss = grad_fn(param_vals, key, inputs,
                                            label)
            new_params, new_state = self._apply_updates(
                param_vals, mean_grads, opt_state, lr)
            return new_params, new_state, mean_loss

        donate = (0, 1) if self._donate else ()
        return jax.jit(train_step, donate_argnums=donate)

    def _build_multi(self, n_steps, n_micro):
        """K = ``n_steps`` training steps lowered into ONE XLA program
        (ISSUE 6 tentpole): a ``lax.scan`` over device-resident batches
        with ALL carry state — params, optimizer slots — donated, so the
        host dispatches once per K steps instead of once per step.
        Per-step lrs and PRNG keys arrive as stacked (K,) vectors drawn
        host-side from the SAME streams the per-step path uses, so K>1
        matches K=1 bitwise (the per-step math is _grad_fn +
        _apply_updates, the exact single-step bodies)."""
        grad_fn = self._grad_fn(self._make_loss_of(), n_micro)

        def train_multi(param_vals, opt_state, lrs, keys, *stacked):
            def one_step(carry, xs):
                pv, st = carry
                lr, key = xs[0], xs[1]
                batch = list(xs[2:])
                grads, loss = grad_fn(pv, key, batch[:-1], batch[-1])
                new_p, new_s = self._apply_updates(pv, grads, st, lr)
                return (new_p, new_s), loss

            (new_params, new_state), losses = lax.scan(
                one_step, (list(param_vals), opt_state),
                (lrs, keys) + tuple(stacked))
            return new_params, new_state, losses

        donate = (0, 1) if self._donate else ()
        return jax.jit(train_multi, donate_argnums=donate)

    def _micro_splitter(self, n_micro):
        def split_micro(b, is_label=False):
            # split each array's own effective BATCH axis into n_micro
            # leading scan slices, preserving the layout within each
            # microbatch (rank-1 labels under batch_axis=1 split on
            # axis 0 — see _eff_bax)
            bax = self._eff_bax(b.ndim, is_label)
            s = b.shape
            b = b.reshape(s[:bax] + (n_micro, s[bax] // n_micro)
                          + s[bax + 1:])
            return jnp.moveaxis(b, bax, 0)
        return split_micro

    def step_accum(self, *batch, n_micro):
        """One fused update from ``n_micro`` microbatches: batch arrays
        carry n_micro * B elements on ``batch_axis`` and are consumed
        microbatch-at-a-time inside the compiled step (see
        :meth:`_build_accum`).  Returns the mean microbatch loss."""
        if n_micro < 1:
            raise MXNetError("step_accum: n_micro must be >= 1")
        if self._pp_active():
            return self._pp_step(batch, n_micro=n_micro)
        t_step = _telem.clock() if _telem.enabled() else None
        trc = _trace.enabled()
        tt0 = _trace.clock() if trc else None
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        bax = self._eff_bax(inputs[-1].ndim, is_label=True)
        if inputs[-1].shape[bax] % n_micro:
            raise MXNetError(
                f"step_accum: batch axis {bax} size "
                f"{inputs[-1].shape[bax]} not divisible by n_micro "
                f"{n_micro}")
        if self._param_objs is None:
            # one-microbatch probe resolves deferred shapes (sliced on
            # each input's own effective batch axis); skipped once
            # params exist
            probe = [NDArray(jnp.take(
                b, jnp.arange(max(1, b.shape[self._eff_bax(b.ndim)]
                                  // n_micro)),
                axis=self._eff_bax(b.ndim))) for b in inputs[:-1]]
            params = self._collect(*probe)
        else:
            params = self._param_objs
        if self._zero1_active():
            self._zero1_ensure_plan(inputs)
        self._ensure_device_state(params)
        if self._zero1_active():
            dp = self.mesh.shape[AXIS_DP]
            b = inputs[-1].shape[bax]
            if b % dp or (b // dp) % n_micro:
                raise MXNetError(
                    f"step_accum under shard_updates: batch {b} must "
                    f"split evenly over dp={dp} chips x n_micro="
                    f"{n_micro} microbatches (set MXTPU_SHARDED_SYNC=0 "
                    f"or adjust the batch)")
            jitted = self._get_zero1_jit("accum", inputs, n_micro=n_micro)
        else:
            jitted = self._jit_accum_cache.get(n_micro)
            if jitted is None:
                jitted = self._build_accum(n_micro)
                self._jit_accum_cache[n_micro] = jitted
        tt1 = _trace.clock() if trc else None
        inputs = self._put_batch(inputs)
        tt2 = _trace.clock() if trc else None
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = self._dispatch(
            jitted, self._param_vals, self._opt_state, lr, key, *inputs)
        tt3 = _trace.clock() if trc else None
        self._num_update += 1
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        self._record_step(1, t_step)
        if trc:
            self._trace_step_phases(tt0, tt1, tt2, tt3)
        return NDArray(loss)

    def _build_indexed(self):
        body = self._step_body()

        def train_step(param_vals, opt_state, lr, key, superdata,
                       superlabel, i):
            data = jax.lax.dynamic_index_in_dim(superdata, i, 0,
                                                keepdims=False)
            label_b = jax.lax.dynamic_index_in_dim(superlabel, i, 0,
                                                   keepdims=False)
            return body(param_vals, opt_state, lr, key, [data], label_b)

        donate = (0, 1) if self._donate else ()
        self._jitted_indexed = jax.jit(train_step, donate_argnums=donate)

    # -- ZeRO-1 sharded gradient sync (the bucketed RS+AG pipeline) -----
    def _zero1_active(self):
        """Resolve (once) whether the sharded pipeline runs: needs
        ``shard_updates=True``, dp > 1, the kill switch off, an
        elementwise update rule (sgd/nag/adam/adamw/rmsprop — lamb/lars
        need per-parameter norms and keep the psum path), and pure data
        parallelism (any tp-sharded parameter falls back)."""
        if self._zero1 is None:
            self._zero1 = (
                self._shard_updates
                and _zero.sharded_sync_enabled()
                and self._rule_name in _zero.ZERO1_RULES
                and self._param_objs is not None
                and all(p.shard_spec is None for p in self._param_objs))
        return self._zero1

    def _zero1_ensure_plan(self, probe_inputs=None):
        """Build the bucket plan once.  With overlap on and a batch
        signature available, the fill order is the REVERSE of the
        forward parameter-use order (one abstract trace, no FLOPs) —
        buckets then complete early-to-late during the XLA backward, so
        each bucket's reduce-scatter is data-ready long before the
        backward finishes and the latency-hiding scheduler
        (``MXTPU_LHS=1``) can sink it under the remaining compute.
        ``MXTPU_OVERLAP_COMM=0`` (or no batch: checkpoint restore)
        keeps PR 3's declaration-order fill bitwise."""
        if self._plan is None:
            order = None
            if self._overlap_comm and probe_inputs is not None:
                order = self._probe_backward_order(probe_inputs)
            self._plan = _zero.BucketPlan(
                [tuple(p.shape) for p in self._param_objs],
                self.mesh.shape[AXIS_DP], fill_order=order)
        return self._plan

    def _probe_backward_order(self, inputs):
        """Parameter indices in expected backward gradient-ready order:
        record first-use order over ONE abstract forward
        (``jax.eval_shape`` — trace only, nothing computes) and reverse
        it.  Returns None (declaration order) if the probe cannot run."""
        from ..gluon.parameter import record_param_use
        params = self._param_objs
        # the abstract forward can WRITE tracers into parameter state
        # (batch-norm running stats update through _set_data during the
        # trace); snapshot the raw buffers and restore unconditionally,
        # or the leaked tracers blow up the next device_put
        snapshot = [(p._data, p._data._data) for p in params
                    if p._data is not None]
        try:
            loss_of = self._make_loss_of()

            def struct(a):
                return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

            pv = [jax.ShapeDtypeStruct(tuple(p.shape),
                                       p.data().data.dtype)
                  for p in params]
            rec = record_param_use()
            with rec:
                jax.eval_shape(
                    loss_of, pv, jax.random.PRNGKey(0),
                    [struct(b) for b in inputs[:-1]], struct(inputs[-1]))
            pos = {id(p): i for i, p in enumerate(params)}
            used = [pos[id(p)] for p in rec.order if id(p) in pos]
            rest = [i for i in range(len(params)) if i not in set(used)]
            # params used EARLIEST in forward get their grads LAST;
            # never-used params (frozen branches) go to the tail buckets
            return list(reversed(used)) + rest if used else None
        except Exception:  # noqa: BLE001 — the probe is an optimization,
            # never a correctness gate; declaration order always works
            return None
        finally:
            for arr, raw in snapshot:
                arr._data = raw

    def _zero1_state_spec_tree(self):
        """shard_map specs for the bucket optimizer state: vector leaves
        (per-element state) shard over 'dp', scalar leaves (step
        counters) replicate."""
        return jax.tree.map(
            lambda x: P(AXIS_DP) if getattr(x, "ndim", 0) >= 1 else P(),
            self._opt_state)

    def _zero1_sync_update(self, param_vals, grads, opt_local, lr, key,
                           comm_mode="overlap"):
        """Bucketed reduce-scatter -> 1/N optimizer update -> all-gather.
        Runs INSIDE shard_map ('dp' bound); ``grads`` are this chip's
        LOCAL gradients, ``opt_local`` the local 1/dp state shards.  ONE
        source for plain/accum/indexed sharded steps.

        ``comm_mode`` exists for the with-vs-without-overlap probe
        (:meth:`overlap_probe`):

        - ``"overlap"`` (the training path): each bucket's flat gradient
          — and therefore its reduce-scatter — is data-dependent ONLY on
          that bucket's own parameters' grads, so with a backward-ordered
          plan the latency-hiding scheduler can launch bucket b's
          collective while buckets b+1.. are still in backward compute.
        - ``"mono"``: an ``optimization_barrier`` ties every bucket's
          payload to ALL gradients and chains the buckets, modeling the
          PR 3 all-comm-after-backward schedule.
        - ``"none"``: collectives replaced by shape-identical local ops
          (slice / tile) — the pure-compute baseline the probe subtracts.
        """
        plan = self._plan
        dp = self.mesh.shape[AXIS_DP]
        mode = self._comm_dtype
        idx = lax.axis_index(AXIS_DP)
        gflats = plan.flatten(grads)
        pflats = plan.flatten(param_vals)
        if comm_mode == "mono":
            # every bucket now depends on the WHOLE backward
            gflats = list(lax.optimization_barrier(tuple(gflats)))
        new_pflats, new_state = [], []
        prev_shard = None
        for b in range(plan.n_buckets):
            ls = plan.shard_length(b)
            gflat = gflats[b]
            if comm_mode == "mono" and prev_shard is not None:
                # serialize bucket b's collective behind bucket b-1's
                gflat, _ = lax.optimization_barrier((gflat, prev_shard))
            if comm_mode == "none":
                gshard = lax.dynamic_slice(gflat, (idx * ls,), (ls,))
            else:
                gshard = _zero.reduce_scatter_bucket(
                    gflat, jax.random.fold_in(key, b), dp, mode)
            prev_shard = gshard
            pshard = lax.dynamic_slice(pflats[b], (idx * ls,), (ls,))
            # flat 1/N shard update: ONE fused kernel walks the bucket
            # (Pallas on TPU, the identical fused_rule chain elsewhere)
            np_, ns = self._bucket_apply(pshard, gshard, opt_local[b], lr)
            if comm_mode == "none":
                new_pflats.append(jnp.tile(np_, dp))
            else:
                new_pflats.append(lax.all_gather(np_, AXIS_DP, tiled=True))
            new_state.append(ns)
        return plan.unflatten(new_pflats, param_vals), new_state

    def _get_zero1_jit(self, kind, inputs, n_micro=None, n_steps=None,
                       comm_mode="overlap", donate=None):
        """Build (and cache per input-rank signature) the jitted
        shard_map step.  Unlike the psum path, shard_map needs the
        in/out specs — hence ranks — up front; jit would retrace per
        shape anyway, so this costs nothing extra."""
        self._zero1_ensure_plan()
        sig = (kind, n_micro, n_steps, tuple(b.ndim for b in inputs),
               comm_mode, donate)
        jitted = self._jit_zero1_cache.get(sig)
        if jitted is not None:
            return jitted
        mesh = self.mesh
        n_in = len(inputs)
        grad_fn = self._grad_fn(self._make_loss_of(),
                                n_micro if kind in ("accum", "multi")
                                and n_micro else 1)

        def local_step(param_vals, opt_local, lr, key, ins, label):
            """One sharded step: per-chip grads -> pmean loss -> the
            bucketed RS -> 1/N update -> AG pipeline.  Shared by every
            kind; the multi-step scan body IS this function."""
            # per-chip PRNG stream (dropout etc. draws fresh per chip)
            key = jax.random.fold_in(key, lax.axis_index(AXIS_DP))
            grads, loss = grad_fn(param_vals, key, ins, label)
            loss = lax.pmean(loss, AXIS_DP)
            new_params, new_state = self._zero1_sync_update(
                param_vals, grads, opt_local, lr,
                jax.random.fold_in(key, 0x5eed), comm_mode=comm_mode)
            return new_params, new_state, loss

        if kind == "multi":
            def local_body(param_vals, opt_local, lrs, keys, *stacked):
                def one_step(carry, xs):
                    pv, st = carry
                    lr, key = xs[0], xs[1]
                    batch = list(xs[2:])
                    new_p, new_s, loss = local_step(
                        pv, st, lr, key, batch[:-1], batch[-1])
                    return (new_p, new_s), loss

                (pv, st), losses = lax.scan(
                    one_step, (list(param_vals), opt_local),
                    (lrs, keys) + tuple(stacked))
                return pv, st, losses
        else:
            def local_body(param_vals, opt_local, lr, key, *batch):
                if kind == "indexed":
                    superdata, superlabel, i = batch
                    data = lax.dynamic_index_in_dim(superdata, i, 0,
                                                    keepdims=False)
                    label = lax.dynamic_index_in_dim(superlabel, i, 0,
                                                     keepdims=False)
                    ins = [data]
                else:
                    ins, label = list(batch[:-1]), batch[-1]
                return local_step(param_vals, opt_local, lr, key, ins,
                                  label)

        pspecs = [P()] * len(self._param_vals)
        sspecs = self._zero1_state_spec_tree()
        if kind == "indexed":
            dspec, lspec = inputs[0], inputs[1]   # prebuilt epoch specs
            batch_specs = (dspec, lspec, P())
        elif kind == "multi":
            # per-step batches stacked on a leading replicated K axis;
            # the within-batch sharding follows the same _eff_bax rule
            batch_specs = tuple(
                self._stacked_spec(b.ndim + 1, is_label=(i == n_in - 1))
                for i, b in enumerate(inputs))
        else:
            batch_specs = tuple(
                self._batch_spec(b.ndim, is_label=(i == n_in - 1))
                for i, b in enumerate(inputs))
        in_specs = (pspecs, sspecs, P(), P()) + batch_specs
        out_specs = (pspecs, sspecs, P())
        wrapped = shard_map(local_body, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False)
        if donate is None:
            donate = self._donate
        jitted = jax.jit(wrapped,
                         donate_argnums=(0, 1) if donate else ())
        self._jit_zero1_cache[sig] = jitted
        return jitted

    def _zero1_check_batch(self, inputs):
        dp = self.mesh.shape[AXIS_DP]
        for i, b in enumerate(inputs):
            ax = self._eff_bax(b.ndim, is_label=(i == len(inputs) - 1))
            if b.shape[ax] % dp:
                raise MXNetError(
                    f"shard_updates: batch axis {ax} size {b.shape[ax]} "
                    f"not divisible by dp={dp} (the sharded pipeline "
                    f"needs even shards; MXTPU_SHARDED_SYNC=0 restores "
                    f"the psum path)")

    # -- pipeline parallelism (ISSUE 11: pp axis of the MeshConfig) -----
    def _pp_active(self):
        return self.mesh_config.pp > 1

    def _pp_ensure(self):
        """Build the 1F1B stage executor once: split the block into
        ``pp`` contiguous stages and give each its ``dp [x tp]``
        submesh (``MeshConfig.stage_mesh``) — stage params/optimizer
        state live ONLY there."""
        if self._pp_exec is None:
            from .pipeline_parallel import (PipelineStageExecutor,
                                            split_into_stages)
            stages = split_into_stages(self.block, self.mesh_config.pp)
            devices = list(_np.asarray(self.mesh.devices).reshape(-1))
            self._pp_exec = PipelineStageExecutor(
                stages, self.loss_fn, self.mesh_config, devices,
                self._rule_init, self._rule_apply,
                self._pp_microbatches)
        return self._pp_exec

    def _pp_step(self, batch, n_micro=1):
        """One pp training step (step/step_accum/step_multi all land
        here): the executor runs M = pp_microbatches * n_micro
        microbatches through the 1F1B schedule.  Loss semantics match
        the flat step: the mean of equal-size microbatch means IS the
        full-batch mean."""
        t_step = _telem.clock() if _telem.enabled() else None
        if self.batch_axis != 0 or self._label_bax != 0:
            raise MXNetError(
                "pipeline parallelism supports batch_axis=0 only")
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        if len(inputs) != 2:
            raise MXNetError(
                "pipeline parallelism expects (data, label) batches — "
                "a Sequential stage chain has one activation stream")
        self._collect(NDArray(inputs[0]))
        ex = self._pp_ensure()
        key = _rnd.next_key()
        lr = self.learning_rate
        trc = _trace.enabled()
        tt0 = _trace.clock() if trc else None
        loss = ex.step(inputs[0], inputs[1], key, lr, n_micro=n_micro)
        self._num_update += 1
        self._record_step(1, t_step)
        if trc:
            # host-driven 1F1B: the stage executor owns the inner
            # schedule, so the step is one dispatch-phase span
            root = _trace.record("train.step", tt0, _trace.clock(),
                                 step=self._num_update, pp=True)
            _trace.record("train.phase.dispatch", tt0, root.t1,
                          parent=root)
        return NDArray(loss)

    # -- telemetry (ISSUE 9) --------------------------------------------
    def _dispatch(self, jitted, *args):
        """Run one compiled step dispatch, timed into the telemetry
        registry (``train.dispatch_ms`` — HOST dispatch time; jax
        returns before the device finishes, so device time lives in the
        profiler/XLA trace, not here).  An unhandled dispatch exception
        dumps the flight recorder before re-raising."""
        t0 = _telem.clock() if _telem.enabled() else None
        if t0 is not None:
            # live MFU (ISSUE 14): resolve this compiled step's XLA FLOP
            # cost BEFORE dispatch (the args are donated by the call) —
            # at most once per jitted object, and only when the chip
            # peak is known (never on a plain CPU host)
            self._maybe_live_cost(jitted, args)
        try:
            out = jitted(*args)
        except Exception as e:  # noqa: BLE001 — record, then re-raise
            _telem.on_step_error(self._num_update, e)
            raise
        if _donation._ENABLED and self._donate:
            # every step variant donates positions (0, 1) — the param
            # and optimizer-state buffers are dead past this point; any
            # later host touch of them is the TPU crash, caught on CPU
            _donation.poison(args[:2],
                             site="DataParallelTrainer._dispatch")
        if t0 is not None:
            _telem.observe("train.dispatch_ms",
                           (_telem.clock() - t0) * 1e3)
        return out

    def _maybe_live_cost(self, jitted, args):
        """Cache the compiled step's XLA FLOP estimate (once per jitted
        — the dict keeps the jitted alive so ids can't be reused) and
        remember it as the cost of the step being dispatched."""
        key = id(jitted)
        hit = self._live_cost.get(key)
        if hit is None:
            flops = (_costmodel.compiled_flops(jitted, *args)
                     if _costmodel.live_cost_enabled() else None)
            hit = (jitted, flops)
            self._live_cost[key] = hit
        self._last_step_flops = hit[1]

    def _record_step(self, k, t_step0):
        """Publish per-step metrics after ``k`` steps committed; the
        ambient telemetry step context feeds event records and profiler
        span tags.  When the compiled step's FLOP cost is known, the
        live ``train.mfu`` / ``train.tflops_delivered`` gauges are O(1)
        arithmetic on top; the health watchdog ticks at the same seam."""
        if t_step0 is None:
            return
        dt_s = _telem.clock() - t_step0
        _telem.set_context(step=self._num_update)
        _telem.inc("train.steps", k)
        _telem.observe("train.step_ms", dt_s * 1e3 / max(k, 1))
        _telem.set_gauge("train.num_update", self._num_update)
        flops = self._last_step_flops
        if flops and dt_s > 0:
            if self._live_peak == ():
                self._live_peak = _costmodel.chip_peak_flops()
            _telem.set_gauge("train.step_flops", flops / max(k, 1))
            _telem.set_gauge("train.tflops_delivered",
                             round(flops / dt_s / 1e12, 4))
            if self._live_peak:
                _telem.set_gauge("train.mfu",
                                 round(flops / dt_s / self._live_peak, 4))
        _watchdog.on_step(self._num_update,
                          step_ms=dt_s * 1e3 / max(k, 1))
        if self._mem_gauges_stale:
            self._publish_memory_gauges()

    def _publish_memory_gauges(self):
        """One-time (per build) exact byte gauges for the flight
        recorder's ``memory`` block (ISSUE 15): the device-resident
        param bytes this trainer owns and its per-chip optimizer-state
        bytes (``train.zero1_shard_bytes`` when ZeRO-1 shards it, the
        replicated ``train.opt_state_bytes`` otherwise).  Exact
        arithmetic on shapes already in hand — no device traffic."""
        self._mem_gauges_stale = False
        try:
            if self._param_vals is not None:
                pbytes = sum(leaf.size * leaf.dtype.itemsize
                             for leaf in jax.tree.leaves(self._param_vals))
                _telem.set_gauge("train.param_bytes", int(pbytes))
            if self._opt_state is not None:
                dp = self.mesh.shape.get(AXIS_DP, 1)
                zero1 = bool(self._zero1 and self._plan is not None)
                sbytes = 0
                for leaf in jax.tree.leaves(self._opt_state):
                    nbytes = leaf.size * leaf.dtype.itemsize
                    # ZeRO-1: vector leaves are dp-sharded, scalars
                    # replicate (the comm_stats accounting)
                    sbytes += nbytes // dp if zero1 and leaf.ndim >= 1 \
                        else nbytes
                _telem.set_gauge("train.zero1_shard_bytes" if zero1
                                 else "train.opt_state_bytes",
                                 int(sbytes))
        except Exception:  # noqa: BLE001 — observability never takes
            pass           # a training step down

    def _trace_step_phases(self, t0, t1, t2, t3):
        """Commit the per-step phase span tree (ISSUE 14): one
        ``train.step`` root whose children tile it exactly —
        prepare (param collect / plan / device state), h2d (batch
        placement), dispatch (the compiled call), commit (host-side
        param bookkeeping + metric publication)."""
        t4 = _trace.clock()
        root = _trace.record("train.step", t0, t4, step=self._num_update)
        _trace.record("train.phase.prepare", t0, t1, parent=root)
        _trace.record("train.phase.h2d", t1, t2, parent=root)
        _trace.record("train.phase.dispatch", t2, t3, parent=root)
        _trace.record("train.phase.commit", t3, t4, parent=root)

    # -- public API -----------------------------------------------------
    @property
    def learning_rate(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler(self._num_update)
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = lr

    def step(self, *batch):
        """batch = (*inputs, label) NDArrays. Returns the scalar loss
        NDArray."""
        if self._pp_active():
            return self._pp_step(batch)
        t_step = _telem.clock() if _telem.enabled() else None
        trc = _trace.enabled()
        tt0 = _trace.clock() if trc else None
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        params = self._collect(*[NDArray(b) for b in inputs[:-1]])
        if self._zero1_active():
            # plan BEFORE device state: the bucket-sharded optimizer
            # state is laid out in plan (fill-order) space
            self._zero1_ensure_plan(inputs)
        self._ensure_device_state(params)
        if self._zero1_active():
            self._zero1_check_batch(inputs)
            jitted = self._get_zero1_jit("plain", inputs)
        else:
            if self._jitted is None:
                self._build()
            jitted = self._jitted
        tt1 = _trace.clock() if trc else None
        inputs = self._put_batch(inputs)
        tt2 = _trace.clock() if trc else None
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = self._dispatch(
            jitted, self._param_vals, self._opt_state, lr, key, *inputs)
        tt3 = _trace.clock() if trc else None
        self._num_update += 1
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        self._record_step(1, t_step)
        if trc:
            self._trace_step_phases(tt0, tt1, tt2, tt3)
        return NDArray(loss)

    def step_multi(self, batches, n_micro=1):
        """K training steps in ONE compiled dispatch (ISSUE 6 tentpole).

        ``batches``: sequence of K per-step batches, each the same
        ``(*inputs, label)`` tuple :meth:`step` takes (all K must share
        shapes — the scan is one trace).  ``n_micro`` > 1 composes with
        in-graph gradient accumulation: each of the K steps is itself a
        ``step_accum``-style microbatch scan.  Returns the (K,) vector
        of per-step losses as one NDArray — read it AFTER the dispatch
        returns; one host sync per K steps is the point.

        Bitwise contract: K steps through here produce exactly the
        params/optimizer state/losses of K consecutive ``step`` (or
        ``step_accum``) calls — per-step lrs and PRNG keys are drawn
        host-side from the same streams, and the step body is the same
        ``_grad_fn``/update code.  ``MXTPU_STEPS_PER_CALL=1`` (the
        default) keeps K-aware loops (estimator/bench) on the per-step
        entry points, restoring today's graphs exactly.
        """
        t_step = _telem.clock() if _telem.enabled() else None
        trc = _trace.enabled()
        tt0 = _trace.clock() if trc else None
        batches = list(batches)
        k = len(batches)
        if k < 1:
            raise MXNetError("step_multi: need at least one batch")
        if n_micro < 1:
            raise MXNetError("step_multi: n_micro must be >= 1")
        if self._pp_active():
            # the pp schedule is host-driven — K steps run as K
            # consecutive 1F1B windows (identical math to K=1 by
            # construction; the scan fusion is a flat-mesh feature)
            losses = [self._pp_step(bt, n_micro=n_micro).data
                      for bt in batches]
            return NDArray(jnp.stack(losses))
        steps = [[b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in bt] for bt in batches]
        first = steps[0]
        n_in = len(first)
        for s in steps[1:]:
            if len(s) != n_in or any(
                    tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype
                    for a, b in zip(s, first)):
                raise MXNetError(
                    "step_multi: all K batches must share shapes/dtypes "
                    "(one scan trace covers the whole window)")
        bax = self._eff_bax(first[-1].ndim, is_label=True)
        if first[-1].shape[bax] % n_micro:
            raise MXNetError(
                f"step_multi: batch axis {bax} size "
                f"{first[-1].shape[bax]} not divisible by n_micro "
                f"{n_micro}")
        params = self._collect(*[NDArray(b) for b in first[:-1]])
        if self._zero1_active():
            self._zero1_ensure_plan(first)
        self._ensure_device_state(params)
        if self._zero1_active():
            self._zero1_check_batch(first)
            dp = self.mesh.shape[AXIS_DP]
            if n_micro > 1 and (first[-1].shape[bax] // dp) % n_micro:
                raise MXNetError(
                    f"step_multi under shard_updates: batch "
                    f"{first[-1].shape[bax]} must split evenly over "
                    f"dp={dp} chips x n_micro={n_micro} microbatches")
            jitted = self._get_zero1_jit("multi", first, n_micro=n_micro,
                                         n_steps=k)
        else:
            jitted = self._jit_multi_cache.get((k, n_micro))
            if jitted is None:
                jitted = self._build_multi(k, n_micro)
                self._jit_multi_cache[(k, n_micro)] = jitted
        tt1 = _trace.clock() if trc else None
        stacked = self._put_stacked(steps)
        tt2 = _trace.clock() if trc else None
        # per-step keys/lrs drawn from the SAME host streams the K=1
        # path uses — this is what makes K>1 bitwise-match K=1
        keys = jnp.stack([_rnd.next_key() for _ in range(k)])
        if self._lr_scheduler is not None:
            lrs = [float(self._lr_scheduler(self._num_update + i))
                   for i in range(k)]
        else:
            lrs = [self._lr] * k
        lrs = jnp.asarray(lrs, jnp.float32)
        new_params, self._opt_state, losses = self._dispatch(
            jitted, self._param_vals, self._opt_state, lrs, keys,
            *stacked)
        tt3 = _trace.clock() if trc else None
        self._num_update += k
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        self._record_step(k, t_step)
        if trc:
            self._trace_step_phases(tt0, tt1, tt2, tt3)
        return NDArray(losses)

    def put_epoch(self, superdata, superlabel):
        """Upload an epoch of batches to device once: superdata
        (n_batches, B, ...), superlabel (n_batches, B, ...). Returns an
        opaque handle for :meth:`step_indexed`.

        Device-resident epoch feeding: per step only a scalar index
        crosses host->device; the batch select is an in-graph
        ``dynamic_index``. This is the TPU analog of the reference's
        PrefetcherIter keeping decoded batches pinned
        (src/io/iter_prefetcher.h) — and on remote-tunneled hosts it
        avoids the per-step H2D dispatch stall entirely.
        """
        if self._pp_active():
            raise MXNetError(
                "put_epoch/step_indexed are flat-mesh entry points; "
                "with a pp axis use step()/step_accum()/step_multi()")
        mesh = self.mesh
        sd = jnp.asarray(superdata.data if isinstance(superdata, NDArray)
                         else superdata)
        sl = jnp.asarray(superlabel.data if isinstance(superlabel, NDArray)
                         else superlabel)
        def epoch_spec(a, is_label=False):
            # leading epoch axis replicated; the within-batch sharding
            # follows the same _eff_bax rule as step()/step_accum()
            if a.ndim < 2:
                raise MXNetError(
                    f"put_epoch expects super-arrays with a leading epoch "
                    f"axis, i.e. (n_batches, batch, ...) with ndim >= 2; "
                    f"got shape {tuple(a.shape)}. Stack per-step batches "
                    f"along a new axis 0 before calling put_epoch.")
            inner = [None] * (a.ndim - 1)
            inner[self._eff_bax(a.ndim - 1, is_label)] = AXIS_DP
            return P(*([None] + inner))

        spec_d = epoch_spec(sd)
        spec_l = epoch_spec(sl, is_label=True)
        # caller owns the handle; dropping it frees the device buffers
        return (jax.device_put(sd, NamedSharding(mesh, spec_d)),
                jax.device_put(sl, NamedSharding(mesh, spec_l)),
                (spec_d, spec_l))

    def _ensure_device_state(self, params):
        """Params stay resident on device across steps (VERDICT r1 weak
        #6: re-device_put per step put a host round on the timed path).
        Only a parameter externally mutated since our last write (identity
        check against the cached array) is re-transferred."""
        if self._pp_active():
            # pp-staged state lives with the stage executor (each
            # stage's submesh), not in the flat-mesh caches
            self._pp_ensure().ensure_ready()
            return
        if self._param_vals is None:
            self._param_vals = [
                jax.device_put(p.data().data, self._param_sharding(p))
                for p in params]
        else:
            for i, p in enumerate(params):
                if p._data is not None and \
                        p._data._data is not self._param_vals[i]:
                    self._param_vals[i] = jax.device_put(
                        p.data().data, self._param_sharding(p))
        if self._opt_state is None:
            if self._zero1_active():
                # ZeRO-1: optimizer state lives in BUCKET space, each
                # vector leaf a flat (bucket_len,) array physically
                # sharded 1/dp per chip; scalar leaves (step counters)
                # replicate.  This is where the (N-1)/N optimizer-HBM
                # saving comes from.
                plan = self._zero1_ensure_plan()
                shard = NamedSharding(self.mesh, P(AXIS_DP))
                rep = NamedSharding(self.mesh, P())
                self._opt_state = [
                    jax.tree.map(
                        lambda x: jax.device_put(
                            x, shard if getattr(x, "ndim", 0) >= 1
                            else rep),
                        self._rule_init(
                            jnp.zeros((plan.lengths[b],), jnp.float32)))
                    for b in range(plan.n_buckets)]
            else:
                rep = NamedSharding(self.mesh, P())
                self._opt_state = [
                    jax.tree.map(lambda x: jax.device_put(x, rep),
                                 self._rule_init(v))
                    for v in self._param_vals]

    def step_indexed(self, epoch_handle, i):
        """One fused train step on batch ``i`` of a resident epoch
        (see :meth:`put_epoch`)."""
        t_step = _telem.clock() if _telem.enabled() else None
        superdata, superlabel = epoch_handle[0], epoch_handle[1]
        if self._param_objs is None:
            # probe batch only for deferred-shape resolution on first call
            self._collect(NDArray(superdata[0]))
        params = self._param_objs
        if self._zero1_active():
            self._zero1_ensure_plan([superdata[0], superlabel[0]])
        self._ensure_device_state(params)
        if self._zero1_active():
            spec_d, spec_l = epoch_handle[2]
            jitted = self._get_zero1_jit("indexed", (spec_d, spec_l))
        else:
            if self._jitted_indexed is None:
                self._build_indexed()
            jitted = self._jitted_indexed
        key = _rnd.next_key()
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        new_params, self._opt_state, loss = self._dispatch(
            jitted, self._param_vals, self._opt_state, lr, key,
            superdata, superlabel, jnp.asarray(i, jnp.int32))
        self._num_update += 1
        self._param_vals = list(new_params)
        for p, v in zip(params, new_params):
            p._data._set_data(v)
        self._record_step(1, t_step)
        return NDArray(loss)

    # -- elastic membership (mx.elastic, ISSUE 8) -----------------------
    def rebuild(self, mesh):
        """Adopt a new mesh **in place** — the trainer half of an
        elastic reshard (``checkpoint.reshard_in_place`` drives the full
        save-state / rebuild / restore-state sequence).

        Everything derived from the old world size is dropped: the
        ZeRO-1 resolution and :class:`~mxnet_tpu.parallel.zero.BucketPlan`
        (bucket padding divides the dp size, so the plan cannot
        survive), every compiled step (jit caches — the traced programs
        bake the old mesh), and the device-resident params/optimizer
        state (sharded over devices that may no longer be in the mesh).
        Parameters stay in the block and are re-placed on first use;
        optimizer state does NOT survive — reload it via
        :meth:`load_state_dict` (its on-disk/per-parameter form is
        dp-independent by PR 4 design, so any source dp reshards
        bitwise).  The update-counter and lr schedule state are host
        scalars and carry over untouched.

        ``mesh`` may be a ``jax.sharding.Mesh`` or a
        :class:`~mxnet_tpu.parallel.mesh.MeshConfig` — an elastic
        transition re-fences ALL THREE axes through here, not just dp
        (ISSUE 11): the pp stage executor, tp shard placements and the
        ZeRO resolution are all re-derived from the new config."""
        if isinstance(mesh, MeshConfig):
            cfg = mesh.resolve(len(jax.devices()))
            mesh = cfg.build()
        else:
            cfg = MeshConfig.for_mesh(mesh)
        self.mesh = mesh
        self.mesh_config = cfg
        self._pp_exec = None
        self._shard_updates = self._shard_requested and \
            mesh.shape.get(AXIS_DP, 1) > 1 and \
            cfg.tp == 1 and cfg.pp == 1
        self._zero1 = None
        self._plan = None
        self._jitted = None
        self._jitted_indexed = None
        self._jit_accum_cache = {}
        self._jit_multi_cache = {}
        self._jit_zero1_cache = {}
        self._param_vals = None
        self._opt_state = None
        self._mem_gauges_stale = True
        return self

    # -- checkpoint protocol (mx.checkpoint.CheckpointManager) ----------
    def _require_params(self):
        if self._param_objs is None:
            params = sorted(self.block.collect_params().items())
            if any(p._data is None for _, p in params):
                raise MXNetError(
                    "DataParallelTrainer state restore needs resolved "
                    "parameter shapes: restore the net's parameters "
                    "first (CheckpointManager does params before "
                    "trainer) or run one forward")
            self._param_objs = [p for _, p in params]
        self._ensure_device_state(self._param_objs)
        return self._param_objs

    def state_dict(self):
        """Optimizer state in PER-PARAMETER space — dp-independent, so a
        resumed run with a different dp size (or with ``shard_updates``
        toggled) rebuckets/reshards on load instead of being stuck with
        the saved topology.  ZeRO-1 bucket vectors are sliced back to
        per-parameter arrays (the D2H gathers the 1/dp shards); bucket
        scalars (e.g. Adam's ``t``) are identical across buckets and
        saved once."""
        from ..ndarray.ndarray import NDArray as _ND
        arrays, leaves = {}, {}
        if self._pp_active():
            # pp-staged state: the executor's per-stage trees map back
            # to the global (sorted) parameter index — the on-disk form
            # is identical to the replicated save, so a checkpoint
            # written at dp x tp x pp restores into ANY mesh shape
            ex = self._pp_exec
            if ex is not None and ex._opt_state is not None and \
                    self._param_objs is not None:
                pos = {id(p): i for i, p in enumerate(self._param_objs)}
                for _s, _li, p, _val, state in ex.iter_params():
                    gi = pos[id(p)]
                    for name, leaf in state.items():
                        if getattr(leaf, "ndim", 0) >= 1:
                            arrays[f"opt/{gi}/{name}"] = _ND(leaf)
                            leaves[name] = "vec"
                        else:
                            arrays[f"opt/{gi}/{name}"] = _ND(
                                jnp.asarray(leaf))
                            leaves.setdefault(name, "per_param_scalar")
        elif self._opt_state is not None:
            params = self._param_objs
            if self._zero1_active():
                plan = self._zero1_ensure_plan()
                full = {}       # bucket id -> {leaf: host flat vector}
                for b, state_b in enumerate(self._opt_state):
                    full[b] = {}
                    for name, leaf in state_b.items():
                        if getattr(leaf, "ndim", 0) >= 1:
                            full[b][name] = _np.asarray(
                                jax.device_get(leaf))
                            leaves[name] = "vec"
                        elif name not in leaves:
                            arrays[f"opt_scalar/{name}"] = _ND(
                                jnp.asarray(leaf))
                            leaves[name] = "scalar"
                for i, p in enumerate(params):
                    b, off, n = plan.param_span(i)
                    for name, vec in full[b].items():
                        arrays[f"opt/{i}/{name}"] = _ND(jnp.asarray(
                            vec[off:off + n].reshape(plan.shapes[i])))
            else:
                for i, state in enumerate(self._opt_state):
                    for name, leaf in state.items():
                        if getattr(leaf, "ndim", 0) >= 1:
                            arrays[f"opt/{i}/{name}"] = _ND(leaf)
                            leaves[name] = "vec"
                        else:
                            arrays[f"opt/{i}/{name}"] = _ND(
                                jnp.asarray(leaf))
                            leaves.setdefault(name, "per_param_scalar")
        meta = {"kind": "parallel.DataParallelTrainer",
                "rule": self._rule_name,
                "num_update": int(self._num_update),
                "saved_dp": int(self.mesh.shape.get(AXIS_DP, 1)),
                "saved_mesh": self.mesh_config.describe(),
                "zero1": bool(self._opt_state is not None
                              and self._zero1_active()),
                "leaves": leaves}
        return {"arrays": arrays, "meta": meta}

    def load_state_dict(self, d):
        """Inverse of :meth:`state_dict`, resharding for THIS trainer's
        topology: under ZeRO-1 the per-parameter arrays are re-flattened
        into this dp size's bucket plan (padding recomputed) and
        device_put 1/dp-sharded; replicated mode loads per-parameter
        trees.  A checkpoint saved at dp=8 restores at dp=2 (or 1) and
        vice versa."""
        arrays, meta = d["arrays"], d["meta"]
        self._num_update = int(meta.get("num_update", 0))
        leaves = meta.get("leaves", {})
        if not leaves:
            return                  # no optimizer state yet at save time
        params = self._require_params()

        def host(a):
            return _np.asarray(a.asnumpy())

        if self._pp_active():
            # re-stage the per-parameter state onto each stage's submesh
            # (the pp inverse of the branches below; a checkpoint saved
            # at ANY mesh shape — flat dp8, zero1, 2x2x2 — lands here
            # when THIS trainer has a pipeline axis)
            ex = self._pp_ensure()
            ex.ensure_ready()
            pos = {id(p): i for i, p in enumerate(params)}
            for s, li, p, val, _state in list(ex.iter_params()):
                gi = pos[id(p)]
                tmpl = self._rule_init(val)
                new_state = {}
                for name, tleaf in tmpl.items():
                    if tleaf.ndim >= 1:
                        src = host(arrays[f"opt/{gi}/{name}"])
                        new_state[name] = jnp.asarray(
                            src, tleaf.dtype).reshape(tleaf.shape)
                    else:
                        key = f"opt/{gi}/{name}" \
                            if f"opt/{gi}/{name}" in arrays \
                            else f"opt_scalar/{name}"
                        new_state[name] = jnp.asarray(
                            host(arrays[key]).reshape(()), tleaf.dtype)
                ex.set_state(s, li, new_state)
            ex.ensure_ready()       # re-place the restored params
            return

        if self._zero1_active():
            plan = self._zero1_ensure_plan()
            shard = NamedSharding(self.mesh, P(AXIS_DP))
            rep = NamedSharding(self.mesh, P())
            # template fixes the leaf set + dtypes for this rule
            template = self._rule_init(jnp.zeros((1,), jnp.float32))
            new_state = []
            for b in range(plan.n_buckets):
                state_b = {}
                for name in template:
                    if leaves.get(name) == "vec":
                        flat = _np.zeros((plan.lengths[b],), _np.float32)
                        for i in plan.buckets[b]:
                            _, off, n = plan.param_span(i)
                            flat[off:off + n] = host(
                                arrays[f"opt/{i}/{name}"]).reshape(-1)
                        state_b[name] = jax.device_put(
                            jnp.asarray(flat), shard)
                    else:
                        # bucket scalar: ``opt_scalar/<name>`` (zero1
                        # save) or any per-param copy (replicated save —
                        # all params share the value, e.g. Adam's t)
                        key = f"opt_scalar/{name}" \
                            if f"opt_scalar/{name}" in arrays \
                            else f"opt/0/{name}"
                        val = host(arrays[key]).reshape(())
                        state_b[name] = jax.device_put(
                            jnp.asarray(val, template[name].dtype), rep)
                new_state.append(state_b)
            self._opt_state = new_state
        else:
            rep = NamedSharding(self.mesh, P())
            new_state = []
            for i, v in enumerate(self._param_vals):
                template = self._rule_init(v)
                state_i = {}
                for name, tleaf in template.items():
                    if tleaf.ndim >= 1:
                        src = host(arrays[f"opt/{i}/{name}"])
                        state_i[name] = jax.device_put(
                            jnp.asarray(src, tleaf.dtype).reshape(
                                tleaf.shape), rep)
                    else:
                        key = f"opt/{i}/{name}" \
                            if f"opt/{i}/{name}" in arrays \
                            else f"opt_scalar/{name}"
                        state_i[name] = jax.device_put(
                            jnp.asarray(host(arrays[key]).reshape(()),
                                        tleaf.dtype), rep)
                new_state.append(state_i)
            self._opt_state = new_state
        # params themselves were restored into the block; re-place them
        # on the mesh so the next step starts from the restored values
        self._param_vals = [
            jax.device_put(p.data().data, self._param_sharding(p))
            for p in params]

    # -- observability ---------------------------------------------------
    def overlap_probe(self, *batch, iters=5):
        """The with-vs-without-overlap probe (ISSUE 5): time three
        structurally different builds of THIS trainer's sharded step on
        ``batch`` —

        - *overlapped* (the training graph): per-bucket reduce-scatter
          data-dependent only on its own grads, free to ride under
          backward compute;
        - *monolithic*: ``optimization_barrier`` pins every collective
          behind the whole backward and chains the buckets (the PR 3
          schedule);
        - *compute-only*: collectives swapped for shape-identical local
          ops — the baseline both are measured against.

        Returns ``exposed_comm_ms`` (comm left on the overlapped step's
        critical path) and ``overlap_frac`` (share of the serialized
        comm the overlap hides: ``1 - exposed / (mono - compute)``).
        All probe programs are compiled WITHOUT donation, so trainer
        state is untouched.  Zeros when the sharded pipeline is off
        (CPU / dp=1 / kill switch)."""
        import time
        from .. import profiler
        # None = NOT measured (pipeline off) — a 0.0 here would read as
        # "measured: comm is free", which the r04/r05 CPU-fallback rounds
        # showed gets mistaken for evidence
        out = {"exposed_comm_ms": None, "overlap_frac": None,
               "overlapped_step_ms": None, "monolithic_step_ms": None,
               "compute_only_step_ms": None}
        inputs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                  for b in batch]
        params = self._collect(*[NDArray(b) for b in inputs[:-1]])
        if self._zero1_active():
            self._zero1_ensure_plan(inputs)
        self._ensure_device_state(params)
        if not self._zero1_active() or self.mesh.shape.get(AXIS_DP, 1) <= 1:
            return out
        self._zero1_check_batch(inputs)
        dev_inputs = self._put_batch(inputs)
        key = jax.random.PRNGKey(7)
        lr = jnp.asarray(self.learning_rate, jnp.float32)
        t_all0 = time.perf_counter()
        # tracing the probe variants can write tracers into parameter
        # state (batch-norm running stats update during the trace); the
        # probe discards its results, so restore the raw buffers after —
        # unlike step(), nothing overwrites them with concrete values
        snapshot = [(p._data, p._data._data) for p in params
                    if p._data is not None]
        try:
            for mode, field in (("none", "compute_only_step_ms"),
                                ("overlap", "overlapped_step_ms"),
                                ("mono", "monolithic_step_ms")):
                f = self._get_zero1_jit("plain", inputs, comm_mode=mode,
                                        donate=False)
                res = f(self._param_vals, self._opt_state, lr, key,
                        *dev_inputs)
                jax.block_until_ready(res)      # compile off the clock
                t0 = time.perf_counter()
                for _ in range(iters):
                    res = f(self._param_vals, self._opt_state, lr, key,
                            *dev_inputs)
                jax.block_until_ready(res)
                out[field] = round(
                    (time.perf_counter() - t0) / iters * 1e3, 3)
        finally:
            for arr, raw in snapshot:
                arr._data = raw
        profiler.record_span("overlap.probe", t_all0, time.perf_counter())
        comp = out["compute_only_step_ms"]
        exposed = max(0.0, out["overlapped_step_ms"] - comp)
        serial = max(exposed, out["monolithic_step_ms"] - comp)
        out["exposed_comm_ms"] = round(exposed, 3)
        if exposed == 0.0:
            # the step DOES contain the collectives (zero1 ran), yet the
            # overlapped build costs no more than pure compute: the comm
            # is fully hidden at this measurement's resolution
            out["overlap_frac"] = 1.0
        elif serial > 0:
            out["overlap_frac"] = round(
                max(0.0, min(1.0, 1.0 - exposed / serial)), 4)
        # retire the probe's private numbers onto the registry: the
        # bench `comm` block and live scrapers read ONE source (ISSUE 9)
        for field, metric in (("exposed_comm_ms",
                               "train.exposed_comm_ms"),
                              ("overlap_frac", "train.overlap_frac")):
            if out[field] is not None:
                _telem.set_gauge(metric, out[field])
        return out

    def comm_stats(self, measure=False, iters=10, step_ms=None,
                   overlap_stats=None):
        """The per-step ``comm`` block (parallel/zero.py schema): static
        wire accounting always; with ``measure=True`` and dp > 1 the
        collective time is MEASURED by timing a jitted RS+AG-only
        program over this trainer's real bucket shapes (``collective_ms``
        / ``est_ici_gb_s``), and ``overlap_efficiency`` estimates how
        much of it a ``step_ms``-long step could hide.  All fields are
        zeros when the sharded pipeline is off — the schema survives so
        CPU CI regression-tests it (tests/test_bench_line.py)."""
        dp = self.mesh.shape.get(AXIS_DP, 1)
        if self._pp_active():
            # pipeline-staged state: each chip holds only its stage's
            # optimizer state (the pp analog of the ZeRO row below)
            ex = self._pp_exec
            total = ex.state_bytes() if ex is not None else 0
            return _zero.comm_block(
                dp=dp, wire_dtype=self._comm_dtype,
                state_bytes_per_chip=total // self.mesh_config.pp,
                state_bytes_replicated=total)
        state_rep = 0
        if self._opt_state is not None:
            for leaf in jax.tree.leaves(self._opt_state):
                state_rep += leaf.size * leaf.dtype.itemsize
        if not (self._zero1 and self._plan is not None):
            # replicated update: every chip carries the full state copy
            state_chip = state_rep
            return _zero.comm_block(
                dp=dp, wire_dtype=self._comm_dtype,
                state_bytes_per_chip=state_chip,
                state_bytes_replicated=state_rep)
        plan = self._plan
        bytes_rs = plan.wire_bytes(self._comm_dtype)
        bytes_ag = 4 * sum(plan.lengths)
        # per-chip state: vector leaves are dp-sharded, scalars replicate
        state_chip = 0
        for leaf in jax.tree.leaves(self._opt_state):
            nbytes = leaf.size * leaf.dtype.itemsize
            state_chip += nbytes // dp if leaf.ndim >= 1 else nbytes
        coll_ms = gbs = overlap = None     # None = not measured
        if measure and dp > 1:
            coll_ms = self._measure_collectives(iters)
            if coll_ms > 0:
                gbs = (bytes_rs + bytes_ag) / (coll_ms / 1e3) / 1e9
            if step_ms:
                overlap = max(0.0, min(1.0, 1.0 - coll_ms / step_ms))
            _telem.set_gauge("comm.collective_ms", coll_ms)
        ov = overlap_stats or {}
        return _zero.comm_block(
            dp=dp, wire_dtype=self._comm_dtype, buckets=plan.n_buckets,
            bytes_reduced_per_step=bytes_rs,
            bytes_gathered_per_step=bytes_ag,
            grad_bytes_fp32=plan.grad_bytes_fp32(),
            collective_ms=coll_ms, est_ici_gb_s=gbs,
            overlap_efficiency=overlap, zero1=True,
            overlap_comm=self._overlap_comm,
            exposed_comm_ms=ov.get("exposed_comm_ms"),
            overlap_frac=ov.get("overlap_frac"),
            state_bytes_per_chip=state_chip, state_bytes_replicated=state_rep)

    def _measure_collectives(self, iters=10):
        """Wall-time a jitted program containing ONLY this trainer's
        per-step collectives (bucketed RS + param AG) — the measured
        ``collective_ms`` evidence for the comm block."""
        import time
        from .. import profiler
        plan = self._plan
        dp = self.mesh.shape[AXIS_DP]
        mode = self._comm_dtype

        def comm_only(flats, key):
            outs = []
            for b, f in enumerate(flats):
                sh = _zero.reduce_scatter_bucket(
                    f, jax.random.fold_in(key, b), dp, mode)
                outs.append(lax.all_gather(sh, AXIS_DP, tiled=True))
            return outs

        specs = [P()] * plan.n_buckets
        f = jax.jit(shard_map(comm_only, mesh=self.mesh,
                              in_specs=(specs, P()), out_specs=specs,
                              check_vma=False))
        flats = [jnp.ones((n,), jnp.float32) for n in plan.lengths]
        key = jax.random.PRNGKey(0)
        jax.block_until_ready(f(flats, key))        # compile off the clock
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(flats, key)
        jax.block_until_ready(out)
        t1 = time.perf_counter()
        profiler.record_span("comm.collectives", t0, t1)
        return (t1 - t0) / iters * 1e3


def all_reduce_gradients(params, mesh=None, axis=AXIS_DP, kvstore=None,
                         keys=None):
    """Sum parameter gradients across data-parallel workers — the ONE
    implementation behind ``gluon.Trainer.allreduce_grads`` and
    standalone use (they used to be two drifting copies).

    - With ``kvstore``: one batched ``pushpull`` over all pending keys
      (the dist store coalesces into BIGARRAY_BOUND buckets — one wire
      round per bucket, not per tensor).
    - Without: a cross-*process* sum via bucketed allgather (within one
      process an eagerly computed gradient already covers the full local
      batch, so there is nothing to reduce).

    ``grad_req='add'`` accumulation is honored: a gradient is reduced
    exactly ONCE per accumulation cycle (tracked per-buffer; autograd
    writing a fresh gradient or ``zero_grad`` re-arms it), so calling
    ``allreduce_grads()`` manually and then ``step()`` — the reference's
    documented split flow — cannot double-count the cross-worker sum.
    """
    if keys is None:
        keys = list(range(len(params)))
    sel_keys, sel_params, grads = [], [], []
    for k, p in zip(keys, params):
        d = getattr(p, "_data", None)
        if getattr(p, "grad_req", "write") == "null" or d is None or \
                d._grad is None:
            continue
        if getattr(d, "_grad_reduced", False):
            continue            # already summed this accumulation cycle
        sel_keys.append(k)
        sel_params.append(p)
        grads.append(p.grad())
    if not sel_keys:
        return params
    if kvstore is not None:
        kvstore.pushpull(sel_keys, grads, out=grads)
        for p, g in zip(sel_params, grads):
            if g.stype == "row_sparse":
                # keep the compressed pair — .data here would materialize
                # a vocab-sized dense grad and disable the optimizer's
                # lazy row update
                p._data._grad = g
            else:
                p._data._grad = g.data
            p._data._grad_reduced = True
        return params
    if jax.process_count() == 1:
        return params
    from jax.experimental import multihost_utils
    from ..ndarray.sparse import RowSparseNDArray
    if any(isinstance(p._data._grad, RowSparseNDArray)
           for p in sel_params):
        raise MXNetError(
            "all_reduce_gradients: row_sparse grads need a kvstore "
            "(dist_tpu_sync row-aware path); pass kvstore=")
    garrs = [p._data._grad for p in sel_params]
    plan = _zero.BucketPlan([g.shape for g in garrs], dp=1,
                            bound_bytes=_zero.bucket_bound_bytes())
    flats = plan.flatten(garrs)
    summed = []
    for flat in flats:
        stacked = multihost_utils.process_allgather(flat)  # mxlint: disable=HB07 -- one DCN round per >=bucket-bound of payload, not per tensor
        summed.append(jnp.sum(stacked, axis=0))
    for p, g in zip(sel_params, plan.unflatten(summed, garrs)):
        p._data._grad = g
        p._data._grad_reduced = True
    return params
