"""Device mesh construction and distributed init.

Reference counterpart: the process/topology side of ps-lite + launch.py
(SURVEY.md §2.6): DMLC_ROLE/DMLC_PS_ROOT_URI env rendezvous. TPU-native:
``jax.distributed.initialize`` (honoring both JAX-style and DMLC-style env
vars) and ``jax.sharding.Mesh`` over ICI/DCN.
"""
from __future__ import annotations

import os
import threading

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["make_mesh", "local_mesh", "distributed_init", "mesh_scope",
           "current_mesh", "data_sharding", "replicate_sharding",
           "batch_sharding", "P", "MeshConfig", "mesh_config_from_env",
           "parallelism_block", "AXIS_DP", "AXIS_TP", "AXIS_PP"]

_STATE = threading.local()

#: Canonical mesh-axis names (ISSUE 11).  Every module that shards or
#: reduces over an axis imports THESE — a hardcoded "dp"/"tp"/"pp"
#: string outside this file is an mxlint HB17 violation: the axis names
#: are MeshConfig's contract, and literal copies rot silently when the
#: mesh layout changes.
AXIS_DP = "dp"      # data parallel: batch split, grad reduce
AXIS_TP = "tp"      # tensor parallel: weight-matrix split (megatron)
AXIS_PP = "pp"      # pipeline parallel: layer stages, microbatch flow


class MeshConfig:
    """One named-axis device-mesh configuration: ``dp x tp x pp``.

    The single source of truth for how the device pool is carved
    (ISSUE 11 tentpole): ``DataParallelTrainer``, ZeRO bucketing, the
    overlap scheduler, checkpoint resharding and elastic rebuild all
    consume a MeshConfig instead of re-deriving axis names/sizes.

    Any axis of size 1 is DISABLED: it does not appear in the built
    ``jax.sharding.Mesh``, so the default ``MeshConfig(dp=N)`` builds
    exactly the ``Mesh(('dp',), N)`` the flat-dp trainer always used —
    ``MXTPU_MESH`` unset is bitwise today's behavior.

    Axis order in the built mesh is ``(pp, dp, tp)`` outermost-first:
    tp is the most-communicating axis and lands on adjacent ICI
    neighbours, pp needs the least bandwidth and spans the outermost
    dimension — the scaling-book layout.  ``stage_mesh(s)`` slices the
    pipeline axis off, returning stage ``s``'s ``dp x tp`` submesh on
    that stage's physical devices (pipeline-STAGED parameters: each
    stage's params exist only on its slice).
    """

    AXES = (AXIS_DP, AXIS_TP, AXIS_PP)

    def __init__(self, dp=1, tp=1, pp=1):
        for name, v in ((AXIS_DP, dp), (AXIS_TP, tp), (AXIS_PP, pp)):
            if not isinstance(v, int) or (v < 1 and v != -1):
                raise MXNetError(
                    f"MeshConfig: axis {name!r} must be a positive int "
                    f"(or -1 to infer dp), got {v!r}")
        if tp == -1 or pp == -1:
            raise MXNetError("MeshConfig: only the dp axis may be -1")
        self.dp, self.tp, self.pp = dp, tp, pp

    # -- construction ----------------------------------------------------
    @classmethod
    def from_spec(cls, spec):
        """Parse a mesh spec string.

        Two grammars (both case-insensitive, whitespace ignored):

        - tagged: ``"dp8"``, ``"dp4tp2"``, ``"dp2tp2pp2"`` — any subset
          of axes, any order, unlisted axes default to 1;
        - positional: ``"2x2x2"`` (``dp x tp x pp``; trailing axes may
          be omitted: ``"4x2"`` = dp4 tp2).
        """
        import re
        s = str(spec).strip().lower().replace(" ", "")
        if not s:
            raise MXNetError("MeshConfig.from_spec: empty spec")
        if re.fullmatch(r"-?\d+(x-?\d+){0,2}", s):
            sizes = [int(t) for t in s.split("x")]
            sizes += [1] * (3 - len(sizes))
            return cls(dp=sizes[0], tp=sizes[1], pp=sizes[2])
        toks = re.findall(r"(dp|tp|pp)(-?\d+)", s)
        if not toks or "".join(t + n for t, n in toks) != s:
            raise MXNetError(
                f"MXTPU_MESH/mesh spec {spec!r} not understood: use "
                f"'dp8', 'dp2tp2pp2' or 'DPxTPxPP' like '2x2x2'")
        axes = {}
        for name, num in toks:
            if name in axes:
                raise MXNetError(f"mesh spec {spec!r}: axis {name!r} "
                                 f"given twice")
            axes[name] = int(num)
        return cls(**axes)

    @classmethod
    def from_env(cls):
        """The active config from ``MXTPU_MESH`` — None when unset (the
        caller falls back to flat dp over all devices, today's
        behavior)."""
        spec = os.environ.get("MXTPU_MESH", "").strip()
        return cls.from_spec(spec) if spec else None

    @classmethod
    def for_mesh(cls, mesh):
        """Derive the config an existing Mesh implies (axes the mesh
        does not name are size 1)."""
        shape = dict(mesh.shape)
        return cls(dp=int(shape.get(AXIS_DP, 1)),
                   tp=int(shape.get(AXIS_TP, 1)),
                   pp=int(shape.get(AXIS_PP, 1)))

    def resolve(self, n_devices):
        """Infer ``dp=-1`` against a device count; returns a concrete
        MeshConfig."""
        if self.dp != -1:
            return self
        denom = self.tp * self.pp
        if n_devices % denom:
            raise MXNetError(
                f"MeshConfig: {n_devices} devices not divisible by "
                f"tp*pp={denom}")
        return MeshConfig(dp=n_devices // denom, tp=self.tp, pp=self.pp)

    # -- introspection ---------------------------------------------------
    @property
    def size(self):
        return self.dp * self.tp * self.pp

    def axis_size(self, axis):
        return {AXIS_DP: self.dp, AXIS_TP: self.tp,
                AXIS_PP: self.pp}[axis]

    def enabled(self, axis):
        return self.axis_size(axis) > 1

    def as_dict(self):
        return {AXIS_DP: self.dp, AXIS_TP: self.tp, AXIS_PP: self.pp}

    def describe(self):
        """Canonical compact spec, e.g. ``"dp8"`` / ``"dp2tp2pp2"`` —
        round-trips through :meth:`from_spec`."""
        out = f"{AXIS_DP}{self.dp}"
        if self.tp > 1:
            out += f"{AXIS_TP}{self.tp}"
        if self.pp > 1:
            out += f"{AXIS_PP}{self.pp}"
        return out

    def __eq__(self, other):
        return isinstance(other, MeshConfig) and \
            self.as_dict() == other.as_dict()

    def __hash__(self):
        return hash((self.dp, self.tp, self.pp))

    def __repr__(self):
        return f"MeshConfig({self.describe()!r})"

    # -- mesh building ---------------------------------------------------
    def _ordered_axes(self):
        """(name, size) outermost-first: pp, dp, tp — disabled axes
        dropped, dp always present (the one axis the flat trainer
        assumes exists)."""
        axes = []
        if self.pp > 1:
            axes.append((AXIS_PP, self.pp))
        axes.append((AXIS_DP, self.dp))
        if self.tp > 1:
            axes.append((AXIS_TP, self.tp))
        return axes

    def _take_devices(self, devices):
        devices = list(devices) if devices is not None else jax.devices()
        cfg = self.resolve(len(devices))
        if cfg.size > len(devices):
            raise MXNetError(
                f"MeshConfig {cfg.describe()} needs {cfg.size} devices, "
                f"only {len(devices)} available")
        return cfg, devices[:cfg.size]

    def build(self, devices=None):
        """The full ``jax.sharding.Mesh`` (first ``size`` devices of the
        pool)."""
        cfg, devs = self._take_devices(devices)
        names = [n for n, _ in cfg._ordered_axes()]
        sizes = [s for _, s in cfg._ordered_axes()]
        arr = _np.asarray(devs).reshape(sizes)
        return Mesh(arr, tuple(names))

    def stage_mesh(self, stage, devices=None):
        """Pipeline stage ``stage``'s ``dp [x tp]`` submesh — the devices
        that stage's parameters, activations and optimizer state live
        on.  With pp disabled there is exactly one stage: the full
        mesh."""
        cfg, devs = self._take_devices(devices)
        if not 0 <= stage < cfg.pp:
            raise MXNetError(f"stage {stage} out of range for "
                             f"pp={cfg.pp}")
        names = [n for n, _ in cfg._ordered_axes()]
        sizes = [s for _, s in cfg._ordered_axes()]
        arr = _np.asarray(devs).reshape(sizes)
        if cfg.pp > 1:
            arr = arr[stage]
            names = names[1:]
        return Mesh(arr, tuple(names))


def mesh_config_from_env(default_devices=None):
    """Resolve the ambient MeshConfig: ``MXTPU_MESH`` when set, else
    flat dp over the whole pool (bitwise today's default)."""
    cfg = MeshConfig.from_env()
    if cfg is None:
        n = len(default_devices if default_devices is not None
                else jax.devices())
        cfg = MeshConfig(dp=n)
    return cfg.resolve(len(default_devices if default_devices is not None
                           else jax.devices()))


def parallelism_block(config=None, pp_microbatches=None,
                      pp_bubble_frac=None, tp_collective_ms=None):
    """The bench ``parallelism`` observability block (ISSUE 11): mesh
    shape stamped always (it is configuration, not measurement);
    ``pp_bubble_frac`` is the ANALYTIC 1F1B bubble fraction — present
    only when a pipeline axis exists; ``tp_collective_ms`` is MEASURED
    and therefore null-when-unmeasured (CPU / tp=1), per the PR 6
    honesty rule."""
    cfg = config or MeshConfig(dp=1)
    return {
        "mesh": cfg.as_dict(),
        "mesh_spec": cfg.describe(),
        "pp_microbatches": (None if pp_microbatches is None
                            else int(pp_microbatches)),
        "pp_bubble_frac": (None if pp_bubble_frac is None
                           else round(float(pp_bubble_frac), 4)),
        "tp_collective_ms": (None if tp_collective_ms is None
                             else round(float(tp_collective_ms), 3)),
    }


def distributed_init(coordinator=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX. Honors DMLC-style env for launcher compat:
    DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT -> coordinator, DMLC_NUM_WORKER ->
    num_processes, DMLC_WORKER_ID -> process_id (reference: §2.6 env table).
    """
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator = f"{uri}:{port}"
    if num_processes is None and "DMLC_NUM_WORKER" in os.environ:
        num_processes = int(os.environ["DMLC_NUM_WORKER"])
    if process_id is None and "DMLC_WORKER_ID" in os.environ:
        process_id = int(os.environ["DMLC_WORKER_ID"])
    if coordinator is None:
        return False  # single process
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def make_mesh(axes=None, devices=None):
    """Build a Mesh. ``axes`` is a dict name->size (-1 = infer one axis).

    Example: make_mesh({'dp': -1, 'tp': 2}) on 8 devices -> 4x2 mesh.
    Axis order follows insertion order; put the fastest-varying
    (most-communicating, e.g. 'tp') LAST so it lands on adjacent ICI
    neighbours (scaling-book recipe).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    axes = dict(axes or {"dp": n})
    sizes = list(axes.values())
    names = list(axes.keys())
    n_infer = sizes.count(-1)
    if n_infer > 1:
        raise MXNetError("at most one mesh axis may be -1")
    known = 1
    for s in sizes:
        if s != -1:
            known *= s
    if n_infer:
        if n % known:
            raise MXNetError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    arr = _np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def local_mesh(axes=None):
    return make_mesh(axes, jax.local_devices())


class mesh_scope:
    """with mesh_scope(mesh): ... — sets the ambient mesh used by
    DataParallelTrainer / sharded layers."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        stack = getattr(_STATE, "stack", None)
        if stack is None:
            stack = _STATE.stack = []
        stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return False


def current_mesh():
    stack = getattr(_STATE, "stack", None)
    return stack[-1] if stack else None


def data_sharding(mesh, ndim, axis=0, data_axis="dp"):
    """NamedSharding splitting dim `axis` over the data mesh axis."""
    spec = [None] * ndim
    spec[axis] = data_axis
    return NamedSharding(mesh, P(*spec))


def replicate_sharding(mesh):
    return NamedSharding(mesh, P())


def batch_sharding(mesh, ndim, batch_axis=0, data_axis=None):
    """NamedSharding for an input-batch array of rank ``ndim``.

    Splits the batch axis over the mesh's data axis; rank-1 arrays
    (per-sample label vectors) always split on axis 0 whatever the
    nominal ``batch_axis`` (same convention as
    ``DataParallelTrainer._eff_bax``); scalars replicate.  ``data_axis``
    defaults to ``'dp'`` when the mesh has one, else the first mesh
    axis.  Used by ``io.DevicePrefetcher`` to land prefetched batches
    directly on their step-time sharding — no device-side reshard when
    the step consumes them.
    """
    if data_axis is None:
        data_axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
    if ndim == 0:
        return NamedSharding(mesh, P())
    ax = batch_axis if ndim > 1 else 0
    if ax >= ndim:
        raise MXNetError(
            f"batch axis {ax} out of range for rank-{ndim} array")
    spec = [None] * ndim
    spec[ax] = data_axis
    return NamedSharding(mesh, P(*spec))
