"""Device mesh construction and distributed init.

Reference counterpart: the process/topology side of ps-lite + launch.py
(SURVEY.md §2.6): DMLC_ROLE/DMLC_PS_ROOT_URI env rendezvous. TPU-native:
``jax.distributed.initialize`` (honoring both JAX-style and DMLC-style env
vars) and ``jax.sharding.Mesh`` over ICI/DCN.
"""
from __future__ import annotations

import os
import threading

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["make_mesh", "local_mesh", "distributed_init", "mesh_scope",
           "current_mesh", "data_sharding", "replicate_sharding",
           "batch_sharding", "P"]

_STATE = threading.local()


def distributed_init(coordinator=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX. Honors DMLC-style env for launcher compat:
    DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT -> coordinator, DMLC_NUM_WORKER ->
    num_processes, DMLC_WORKER_ID -> process_id (reference: §2.6 env table).
    """
    if coordinator is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator = f"{uri}:{port}"
    if num_processes is None and "DMLC_NUM_WORKER" in os.environ:
        num_processes = int(os.environ["DMLC_NUM_WORKER"])
    if process_id is None and "DMLC_WORKER_ID" in os.environ:
        process_id = int(os.environ["DMLC_WORKER_ID"])
    if coordinator is None:
        return False  # single process
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def make_mesh(axes=None, devices=None):
    """Build a Mesh. ``axes`` is a dict name->size (-1 = infer one axis).

    Example: make_mesh({'dp': -1, 'tp': 2}) on 8 devices -> 4x2 mesh.
    Axis order follows insertion order; put the fastest-varying
    (most-communicating, e.g. 'tp') LAST so it lands on adjacent ICI
    neighbours (scaling-book recipe).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    axes = dict(axes or {"dp": n})
    sizes = list(axes.values())
    names = list(axes.keys())
    n_infer = sizes.count(-1)
    if n_infer > 1:
        raise MXNetError("at most one mesh axis may be -1")
    known = 1
    for s in sizes:
        if s != -1:
            known *= s
    if n_infer:
        if n % known:
            raise MXNetError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    arr = _np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def local_mesh(axes=None):
    return make_mesh(axes, jax.local_devices())


class mesh_scope:
    """with mesh_scope(mesh): ... — sets the ambient mesh used by
    DataParallelTrainer / sharded layers."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        stack = getattr(_STATE, "stack", None)
        if stack is None:
            stack = _STATE.stack = []
        stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return False


def current_mesh():
    stack = getattr(_STATE, "stack", None)
    return stack[-1] if stack else None


def data_sharding(mesh, ndim, axis=0, data_axis="dp"):
    """NamedSharding splitting dim `axis` over the data mesh axis."""
    spec = [None] * ndim
    spec[axis] = data_axis
    return NamedSharding(mesh, P(*spec))


def replicate_sharding(mesh):
    return NamedSharding(mesh, P())


def batch_sharding(mesh, ndim, batch_axis=0, data_axis=None):
    """NamedSharding for an input-batch array of rank ``ndim``.

    Splits the batch axis over the mesh's data axis; rank-1 arrays
    (per-sample label vectors) always split on axis 0 whatever the
    nominal ``batch_axis`` (same convention as
    ``DataParallelTrainer._eff_bax``); scalars replicate.  ``data_axis``
    defaults to ``'dp'`` when the mesh has one, else the first mesh
    axis.  Used by ``io.DevicePrefetcher`` to land prefetched batches
    directly on their step-time sharding — no device-side reshard when
    the step consumes them.
    """
    if data_axis is None:
        data_axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
    if ndim == 0:
        return NamedSharding(mesh, P())
    ax = batch_axis if ndim > 1 else 0
    if ax >= ndim:
        raise MXNetError(
            f"batch axis {ax} out of range for rank-{ndim} array")
    spec = [None] * ndim
    spec[ax] = data_axis
    return NamedSharding(mesh, P(*spec))
