"""Pipeline parallelism over a 'pp' mesh axis — TPU-native GPipe.

Reference capability (SURVEY.md §2.5 "model parallel" row): upstream MXNet
placed layer groups on devices with ``group2ctx`` and moved activations with
explicit copies. The TPU design instead runs ALL stages as one SPMD program:
stage parameters are stacked on a leading axis sharded over 'pp', and one
``lax.scan`` over pipeline ticks moves activations between neighbouring
stages with ``lax.ppermute`` (the activation hop rides ICI, compiled into
the step). Differentiable end-to-end — ``jax.grad`` through the scan gives
the 1F1B-equivalent backward for free, so a pipelined training step is just
``value_and_grad(pipeline_apply)`` under ``jit``.

The schedule is GPipe: with S stages and M microbatches the bubble fraction
is (S-1)/(M+S-1); choose M >= 4*S for <20% bubble (How to Scale Your Model,
pipelining chapter).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from ._compat import shard_map

from ..base import MXNetError
from .mesh import AXIS_DP, AXIS_PP

__all__ = ["pipeline_apply", "pipeline_local", "stack_stage_params",
           "Pipeline", "one_f_one_b_schedule", "bubble_fraction",
           "split_into_stages", "PipelineStageExecutor", "Schedule1F1B"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree with a leading [n_stages] axis — shard it over 'pp'."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_local(stage_fn, params_local, micro_all, *, axis, n_stages,
                   n_microbatches):
    """GPipe tick schedule for use INSIDE an existing shard_map whose mesh
    binds ``axis`` — the composable core shared by ``pipeline_apply`` and
    multi-axis SPMD programs that pipeline alongside dp/tp/sp (mirrors
    ``ring_attention_local``).

    ``params_local``: this stage's (already-squeezed) parameter pytree.
    ``micro_all``: (n_microbatches, mb, ...) — replicated over ``axis``;
    stage 0 ingests from it. Returns the finished (n_microbatches, mb, ...)
    outputs, broadcast to every stage.
    """
    stage = lax.axis_index(axis)
    mb_shape = micro_all.shape[1:]
    n_ticks = n_microbatches + n_stages - 1
    # initial carries must already be device-varying over the pipeline axis
    # so the scan carry type stays fixed (shard_map vma typing); under
    # check_vma=False pcast is unavailable and also unnecessary
    state = _pcast_varying(jnp.zeros(mb_shape, micro_all.dtype), axis)
    outputs = _pcast_varying(jnp.zeros_like(micro_all), axis)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (if still in range); other
        # stages consume what arrived from the left neighbour
        feed_idx = jnp.clip(t, 0, n_microbatches - 1)
        inp = jnp.where(stage == 0, micro_all[feed_idx], state)
        out = stage_fn(params_local, inp)
        # the last stage writes its finished microbatch (t - S + 1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
        write = (stage == n_stages - 1) & (t >= n_stages - 1)
        outputs = jnp.where(
            write,
            outputs.at[out_idx].set(out),
            outputs)
        # shift activations one stage to the right (ring permute; the
        # wrap-around value into stage 0 is ignored — it re-reads
        # micro_all)
        state = lax.ppermute(
            out, axis,
            [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(n_ticks))
    # every device carries a full `outputs` buffer but only the last
    # stage's is real; broadcast it (psum of masked buffer)
    return lax.psum(
        jnp.where(stage == n_stages - 1, outputs,
                  jnp.zeros_like(outputs)), axis)


def _pcast_varying(x, axis):
    try:
        return lax.pcast(x, axis, to="varying")
    except Exception:  # noqa: BLE001 — check_vma=False context: no-op
        return x


def pipeline_apply(stage_fn, stacked_params, x, *, mesh, n_microbatches,
                   axis="pp"):
    """Run ``x`` through S pipeline stages on the mesh's ``axis``.

    stage_fn(params_one_stage, microbatch) -> microbatch' — the same
    callable for every stage (homogeneous pipelining, the transformer
    case). ``stacked_params`` has a leading [S] axis; ``x`` has a leading
    batch axis that is split into ``n_microbatches``.

    Returns the output batch (same leading shape as x). Differentiable.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise MXNetError(
            f"batch {batch} not divisible by n_microbatches "
            f"{n_microbatches}")
    mb = batch // n_microbatches
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])

    def spmd(params_s, micro_all):
        # params_s: this stage's params (leading axis sliced to 1) — squeeze
        params_s = jax.tree.map(lambda a: a[0], params_s)
        return pipeline_local(stage_fn, params_s, micro_all, axis=axis,
                              n_stages=n_stages,
                              n_microbatches=n_microbatches)

    param_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P())
    out = fn(stacked_params, micro)
    return out.reshape((batch,) + out.shape[2:])


class Pipeline:
    """Convenience wrapper: hold stacked params + jit the pipelined forward.

    Example::

        def stage(params, x):
            return jnp.tanh(x @ params["w"] + params["b"])
        pp = Pipeline(stage, [stage0_params, ..., stage3_params],
                      mesh=make_mesh({"pp": 4}), n_microbatches=8)
        y = pp(x)
    """

    def __init__(self, stage_fn, per_stage_params, mesh, n_microbatches,
                 axis="pp"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_microbatches = n_microbatches
        stacked = stack_stage_params(per_stage_params)
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, P(*([axis] + [None] * (a.ndim - 1))))), stacked)
        self._jitted = jax.jit(functools.partial(
            pipeline_apply, stage_fn, mesh=mesh,
            n_microbatches=n_microbatches, axis=axis))

    def __call__(self, x):
        return self._jitted(self.params, x)


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush) schedule + the host-driven stage executor
# (ISSUE 11 tentpole).  The GPipe scan above runs every stage as one SPMD
# program — ideal when stages are homogeneous.  The executor below is the
# trainer-facing half: it pipelines an ARBITRARY (Hybrid)Sequential gluon
# model over per-stage device submeshes (MeshConfig.stage_mesh), running
# the canonical one-forward-one-backward schedule from the host with one
# AOT-jitted forward / recompute-backward / update program per stage.
# Stage parameters and optimizer state exist ONLY on their stage's
# devices (pipeline-staged params, 1/S memory); dp (and tp, via the
# sharding algebra on each stage submesh) compose inside every stage
# program.  When each stage's gradients become FINAL (its last backward
# microbatch), the executor fires the PR 5 grad-ready hooks — so an
# installed OverlapScheduler launches its bucketed dp collectives right
# there, inside the pipeline bubble, while earlier stages are still in
# backward — and dispatches that stage's optimizer update into the same
# bubble.
# ---------------------------------------------------------------------------

def bubble_fraction(n_stages, n_microbatches):
    """Analytic 1F1B bubble fraction: (S-1)/(M+S-1) of the schedule is
    idle per stage (same as GPipe; 1F1B wins on activation memory, not
    bubble).  Choose M >= 4*S for <20%."""
    s, m = int(n_stages), int(n_microbatches)
    if s < 1 or m < 1:
        raise MXNetError("bubble_fraction: need n_stages, n_microbatches"
                         " >= 1")
    return (s - 1) / (m + s - 1)


class Schedule1F1B:
    """The materialized tick table of a 1F1B schedule.

    ``ops_by_stage[s]`` — ``[('F'|'B', microbatch), ...]`` in execution
    order (no idles).  ``ticks`` — per tick, ``{stage: (phase, mb)}``
    for the stages that act.  ``order`` — the flat host dispatch order
    (tick-major; ops within a tick are dependency-free).
    ``bubble_ticks(s)`` — idle ticks of stage ``s`` inside the active
    window.
    """

    def __init__(self, n_stages, n_microbatches, ops_by_stage, ticks):
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.ops_by_stage = ops_by_stage
        self.ticks = ticks
        self.order = [(s, phase, mb)
                      for tick in ticks
                      for s, (phase, mb) in sorted(tick.items())]

    @property
    def n_ticks(self):
        return len(self.ticks)

    def bubble_ticks(self, stage):
        active = [t for t, ops in enumerate(self.ticks) if stage in ops]
        return (active[-1] - active[0] + 1) - len(active)

    @property
    def bubble_frac(self):
        return bubble_fraction(self.n_stages, self.n_microbatches)


def one_f_one_b_schedule(n_stages, n_microbatches):
    """Build the canonical non-interleaved 1F1B schedule (PipeDream-
    flush / Megatron): stage ``s`` runs ``min(M, S-1-s)`` warmup
    forwards, then strictly alternates F,B (one forward, one backward)
    until its M forwards are done, then drains the remaining backwards.
    Dependencies: F(s,m) needs F(s-1,m); B(s,m) needs B(s+1,m) and
    F(s,m).  A stage whose scheduled op is not yet data-ready idles —
    those are the bubbles the executor fills with grad communication
    and optimizer updates."""
    s_n, m_n = int(n_stages), int(n_microbatches)
    if s_n < 1 or m_n < 1:
        raise MXNetError("one_f_one_b_schedule: need n_stages, "
                         "n_microbatches >= 1")
    warmup = [min(m_n, s_n - 1 - s) for s in range(s_n)]
    f_done = [0] * s_n
    b_done = [0] * s_n
    f_tick = [[None] * m_n for _ in range(s_n)]
    b_tick = [[None] * m_n for _ in range(s_n)]
    # strict F/B alternation state once warmup is over ('F' first)
    next_phase = ["F"] * s_n
    ops_by_stage = [[] for _ in range(s_n)]
    ticks = []
    total = 2 * s_n * m_n
    done = 0
    t = 0
    while done < total:
        if t > 2 * total + 2 * s_n:   # defensive: schedule must converge
            raise MXNetError("1F1B schedule failed to converge")
        this = {}
        for s in range(s_n):
            can_f = (f_done[s] < m_n and
                     (s == 0 or (f_tick[s - 1][f_done[s]] is not None and
                                 f_tick[s - 1][f_done[s]] < t)))
            can_b = (b_done[s] < f_done[s] and
                     (s == s_n - 1 or
                      (b_tick[s + 1][b_done[s]] is not None and
                       b_tick[s + 1][b_done[s]] < t)))
            if f_done[s] < warmup[s]:
                want = "F"                       # warmup: forwards only
            elif f_done[s] >= m_n:
                want = "B"                       # cooldown: drain
            else:
                want = next_phase[s]             # steady 1F1B
            if want == "F" and can_f:
                this[s] = ("F", f_done[s])
            elif want == "B" and can_b:
                this[s] = ("B", b_done[s])
            # else: bubble tick for this stage
        for s, (phase, mb) in this.items():
            if phase == "F":
                f_tick[s][mb] = t
                f_done[s] += 1
                if f_done[s] > warmup[s]:
                    next_phase[s] = "B"
            else:
                b_tick[s][mb] = t
                b_done[s] += 1
                next_phase[s] = "F"
            ops_by_stage[s].append((phase, mb))
            done += 1
        ticks.append(this)
        t += 1
    return Schedule1F1B(s_n, m_n, ops_by_stage, ticks)


def split_into_stages(block, n_stages):
    """Partition a ``(Hybrid)Sequential`` gluon block into ``n_stages``
    contiguous child groups, balanced by parameter element count.
    Returns a list of child-block lists.  Only sequential containers
    qualify: their forward IS the composition of their children, which
    is the contract the stage executor relies on (an arbitrary block's
    forward cannot be split from the outside)."""
    from ..gluon import nn as _nn
    if not isinstance(block, (_nn.Sequential, _nn.HybridSequential)):
        raise MXNetError(
            f"pipeline parallelism needs a Sequential/HybridSequential "
            f"model (the forward must be the composition of its "
            f"children); got {type(block).__name__}.  Wrap the stage-"
            f"able body in nn.HybridSequential or set pp=1")
    children = list(block._children.values())
    if len(children) < n_stages:
        raise MXNetError(
            f"cannot split {len(children)} layers into {n_stages} "
            f"pipeline stages")
    weights = []
    for c in children:
        n = 0
        for p in c.collect_params().values():
            if p.shape:
                k = 1
                for d in p.shape:
                    k *= int(d)
                n += k
        weights.append(max(n, 1))
    total = sum(weights)
    stages, cur, acc = [], [], 0
    remaining = list(range(len(children)))
    for i, c in enumerate(children):
        cur.append(c)
        acc += weights[i]
        left = len(children) - i - 1
        need = n_stages - len(stages) - 1
        # close the stage when it reached its fair share — unless the
        # remaining children are exactly enough to fill remaining stages
        if len(stages) < n_stages - 1 and \
                (acc >= total / n_stages or left == need):
            stages.append(cur)
            cur, acc = [], 0
    stages.append(cur)
    assert len(stages) == n_stages and all(stages)
    return stages


class PipelineStageExecutor:
    """Host-driven 1F1B over per-stage submeshes (the trainer's pp
    engine; see module comment above).

    ``stage_children[s]`` — the gluon child blocks of stage ``s`` (from
    :func:`split_into_stages`).  ``config`` — the 3D
    :class:`~mxnet_tpu.parallel.mesh.MeshConfig`; stage ``s`` computes
    on ``config.stage_mesh(s, devices)``.  ``rule_apply(p, g, s, lr)``
    and ``rule_init(p)`` — the trainer's fused optimizer kernels (ONE
    update source with every other path).  Backward is stage-level
    rematerialization: the backward program re-runs the stage forward
    inside ``jax.vjp`` — only stage-boundary activations are stashed
    between phases, the 1F1B memory shape.

    Events land in :attr:`events` per step:
    ``('F'|'B', stage, mb)``, ``('ready', stage)`` (grads final, PR 5
    grad-ready hooks fired — an installed OverlapScheduler launches its
    bucketed collectives HERE, in the bubble), ``('update', stage)``.
    """

    def __init__(self, stage_children, loss_fn, config, devices,
                 rule_init, rule_apply, n_microbatches):
        if config.pp != len(stage_children):
            raise MXNetError(
                f"executor got {len(stage_children)} stages for "
                f"pp={config.pp}")
        self.cfg = config
        self.loss_fn = loss_fn
        self._devices = list(devices)
        self._rule_init = rule_init
        self._rule_apply = rule_apply
        self.n_microbatches = int(n_microbatches)
        if self.n_microbatches < 1:
            raise MXNetError("pp: n_microbatches must be >= 1")
        self.stage_children = stage_children
        # per-stage sorted param objects (sorted by name, the trainer
        # convention — state_dict round-trips through the same order)
        self.stage_params = []
        for chs in stage_children:
            items = []
            for c in chs:
                items.extend(sorted(c.collect_params().items()))
            self.stage_params.append([p for _, p in sorted(items)])
        self.stage_meshes = [config.stage_mesh(s, self._devices)
                             for s in range(config.pp)]
        self._param_vals = None      # [stage][i] device arrays
        self._opt_state = None       # [stage][i] state trees
        self._fwd = {}
        self._bwd = {}
        self._upd = {}
        self.events = []
        self.last_schedule = None

    # -- placement -------------------------------------------------------
    def _param_sharding(self, stage, p):
        mesh = self.stage_meshes[stage]
        if p.shard_spec is not None:
            return NamedSharding(mesh, p.shard_spec)
        return NamedSharding(mesh, P())

    def _batch_sharding(self, stage, ndim):
        mesh = self.stage_meshes[stage]
        spec = [None] * ndim
        if ndim:
            spec[0] = AXIS_DP if AXIS_DP in mesh.axis_names else None
        return NamedSharding(mesh, P(*spec))

    def ensure_ready(self):
        if self._param_vals is None:
            self._param_vals = [
                [jax.device_put(p.data().data,
                                self._param_sharding(s, p))
                 for p in params]
                for s, params in enumerate(self.stage_params)]
        else:
            for s, params in enumerate(self.stage_params):
                for i, p in enumerate(params):
                    if p._data is not None and \
                            p._data._data is not self._param_vals[s][i]:
                        self._param_vals[s][i] = jax.device_put(
                            p.data().data, self._param_sharding(s, p))
        if self._opt_state is None:
            self._opt_state = [
                [jax.tree.map(
                    lambda x: jax.device_put(
                        x, NamedSharding(self.stage_meshes[s], P())),
                    self._rule_init(v)) for v in vals]
                for s, vals in enumerate(self._param_vals)]

    # -- per-stage programs ---------------------------------------------
    def _stage_apply(self, s):
        """(pv, key, x) -> y: the traced forward of stage ``s`` — same
        bind/trace discipline as the trainer's loss closure."""
        from .. import _tape
        from ..ndarray.ndarray import NDArray
        from ..ndarray import random as _rnd
        from ..gluon.parameter import _bind_params
        children = self.stage_children[s]
        params = self.stage_params[s]

        def apply(pv, key, x):
            prev = _tape.set_training(True)
            binding = {p: NDArray(v) for p, v in zip(params, pv)}
            try:
                with _tape.trace_scope(), _bind_params(binding), \
                        _rnd.trace_key_scope(key):
                    out = NDArray(x)
                    for c in children:
                        out = c.forward(out)
            finally:
                _tape.set_training(prev)
            return out.data
        return apply

    def _programs(self, s):
        if s in self._fwd:
            return
        apply = self._stage_apply(s)
        last = s == self.cfg.pp - 1
        loss_fn = self.loss_fn

        def fwd(pv, key, x):
            return apply(list(pv), key, x)

        if last:
            from ..ndarray.ndarray import NDArray

            def loss_of(pv, x, key, label):
                y = apply(list(pv), key, x)
                return jnp.mean(loss_fn(NDArray(y), NDArray(label)).data)

            def bwd(pv, key, x, label):
                val, (gp, gx) = jax.value_and_grad(
                    loss_of, argnums=(0, 1))(list(pv), x, key, label)
                return val, gp, gx
        else:
            def bwd(pv, key, x, gy):
                _, pull = jax.vjp(
                    lambda pv_, x_: apply(list(pv_), key, x_),
                    list(pv), x)
                gp, gx = pull(gy)
                return gp, gx

        rule_apply = self._rule_apply

        def upd(pv, grads, st, lr):
            new_p, new_s = [], []
            for p_, g_, s_ in zip(pv, grads, st):
                np_, ns_ = rule_apply(p_, g_.astype(p_.dtype), s_, lr)
                new_p.append(np_)
                new_s.append(ns_)
            return new_p, new_s

        self._fwd[s] = jax.jit(fwd)
        self._bwd[s] = jax.jit(bwd)
        self._upd[s] = jax.jit(upd)

    # -- the 1F1B step ---------------------------------------------------
    def step(self, x, label, key, lr, n_micro=1):
        """One optimizer step: ``M = n_microbatches * n_micro``
        microbatches through the 1F1B schedule, grads meaned over all
        of them, one update per stage dispatched into that stage's
        bubble.  Returns the scalar mean loss (a jax array)."""
        from .. import _tape
        from .. import telemetry as _telem
        S = self.cfg.pp
        M = self.n_microbatches * max(1, int(n_micro))
        b = x.shape[0]
        if b % M:
            raise MXNetError(
                f"pp: batch {b} not divisible by {M} microbatches "
                f"(pp_microbatches={self.n_microbatches} x n_micro="
                f"{n_micro})")
        mb = b // M
        if self.cfg.dp > 1 and mb % self.cfg.dp:
            raise MXNetError(
                f"pp: microbatch {mb} not divisible by dp={self.cfg.dp}")
        self.ensure_ready()
        for s in range(S):
            self._programs(s)
        sched = one_f_one_b_schedule(S, M)
        self.last_schedule = sched
        micro_x = [jax.device_put(
            x[i * mb:(i + 1) * mb], self._batch_sharding(0, x.ndim))
            for i in range(M)]
        micro_lab = [jax.device_put(
            label[i * mb:(i + 1) * mb],
            self._batch_sharding(S - 1, label.ndim)) for i in range(M)]
        keys = {(s, i): jax.random.fold_in(key, s * 100003 + i)
                for s in range(S) for i in range(M)}
        stash = [[None] * M for _ in range(S)]    # stage input per mb
        acts = [[None] * M for _ in range(S)]     # stage output per mb
        gys = [[None] * M for _ in range(S)]      # cotangent from right
        gacc = [None] * S
        losses = []
        b_count = [0] * S
        self.events = events = []
        for s, phase, i in sched.order:
            if phase == "F":
                if s == 0:
                    xin = micro_x[i]
                else:
                    xin = jax.device_put(
                        acts[s - 1][i],
                        self._batch_sharding(s, acts[s - 1][i].ndim))
                stash[s][i] = xin
                acts[s][i] = self._fwd[s](self._param_vals[s],
                                          keys[(s, i)], xin)
                events.append(("F", s, i))
                continue
            # backward (stage-level remat: re-runs the stage forward)
            if s == S - 1:
                val, gp, gx = self._bwd[s](self._param_vals[s],
                                           keys[(s, i)], stash[s][i],
                                           micro_lab[i])
                losses.append(val)
            else:
                gy = jax.device_put(
                    gys[s][i], self._batch_sharding(s, gys[s][i].ndim))
                gp, gx = self._bwd[s](self._param_vals[s],
                                      keys[(s, i)], stash[s][i], gy)
            if s > 0:
                gys[s - 1][i] = gx
            stash[s][i] = None                     # 1F1B memory shape
            acts[s][i] = None
            if gacc[s] is None:
                gacc[s] = list(gp)
            else:
                gacc[s] = [a + g for a, g in zip(gacc[s], gp)]
            b_count[s] += 1
            events.append(("B", s, i))
            if b_count[s] == M:
                self._finish_stage(s, gacc[s], M, lr, events, _tape,
                                   _telem)
                gacc[s] = None
        loss = jnp.mean(jnp.stack(losses)) if losses else jnp.zeros(())
        # write updated params back into the block (NDArray views on the
        # stage submeshes — checkpoint/parity readers gather on demand)
        for s, params in enumerate(self.stage_params):
            for p, v in zip(params, self._param_vals[s]):
                p._data._set_data(v)
        return loss

    def _finish_stage(self, s, gsum, M, lr, events, _tape, _telem):
        """Stage ``s``'s gradients just became FINAL (its last backward
        microbatch) while earlier stages are still in backward — the
        1F1B bubble.  Everything that only needs THIS stage's grads
        launches now: grad-ready hooks (an installed OverlapScheduler
        dispatches its bucketed dp collectives from them), then the
        stage's optimizer update.  All dispatches are async; nothing
        here blocks on the device."""
        grads = [g / M for g in gsum]
        for p, g in zip(self.stage_params[s], grads):
            if p._data is not None:
                _tape._finalize_leaf(p._data, g)    # fires PR 5 hooks
        events.append(("ready", s))
        if _telem.enabled():
            _telem.event("pp.stage_grads_ready", stage=s)
        new_p, new_s = self._upd[s](self._param_vals[s], grads,
                                    self._opt_state[s],
                                    jnp.asarray(lr, jnp.float32))
        self._param_vals[s] = list(new_p)
        self._opt_state[s] = list(new_s)
        events.append(("update", s))

    # -- state (per-parameter space; the trainer merges stages) ----------
    def iter_params(self):
        """Yield (stage, local_index, param, value, state)."""
        self.ensure_ready()
        for s, params in enumerate(self.stage_params):
            for i, p in enumerate(params):
                yield s, i, p, self._param_vals[s][i], \
                    self._opt_state[s][i]

    def set_state(self, stage, i, state_tree):
        mesh = self.stage_meshes[stage]
        self._opt_state[stage][i] = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x),
                                     NamedSharding(mesh, P())),
            state_tree)

    def state_bytes(self):
        total = 0
        if self._opt_state is not None:
            for leaf in jax.tree.leaves(self._opt_state):
                total += leaf.size * leaf.dtype.itemsize
        return total
