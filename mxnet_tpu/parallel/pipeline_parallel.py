"""Pipeline parallelism over a 'pp' mesh axis — TPU-native GPipe.

Reference capability (SURVEY.md §2.5 "model parallel" row): upstream MXNet
placed layer groups on devices with ``group2ctx`` and moved activations with
explicit copies. The TPU design instead runs ALL stages as one SPMD program:
stage parameters are stacked on a leading axis sharded over 'pp', and one
``lax.scan`` over pipeline ticks moves activations between neighbouring
stages with ``lax.ppermute`` (the activation hop rides ICI, compiled into
the step). Differentiable end-to-end — ``jax.grad`` through the scan gives
the 1F1B-equivalent backward for free, so a pipelined training step is just
``value_and_grad(pipeline_apply)`` under ``jit``.

The schedule is GPipe: with S stages and M microbatches the bubble fraction
is (S-1)/(M+S-1); choose M >= 4*S for <20% bubble (How to Scale Your Model,
pipelining chapter).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from ._compat import shard_map

from ..base import MXNetError

__all__ = ["pipeline_apply", "pipeline_local", "stack_stage_params",
           "Pipeline"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree with a leading [n_stages] axis — shard it over 'pp'."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_local(stage_fn, params_local, micro_all, *, axis, n_stages,
                   n_microbatches):
    """GPipe tick schedule for use INSIDE an existing shard_map whose mesh
    binds ``axis`` — the composable core shared by ``pipeline_apply`` and
    multi-axis SPMD programs that pipeline alongside dp/tp/sp (mirrors
    ``ring_attention_local``).

    ``params_local``: this stage's (already-squeezed) parameter pytree.
    ``micro_all``: (n_microbatches, mb, ...) — replicated over ``axis``;
    stage 0 ingests from it. Returns the finished (n_microbatches, mb, ...)
    outputs, broadcast to every stage.
    """
    stage = lax.axis_index(axis)
    mb_shape = micro_all.shape[1:]
    n_ticks = n_microbatches + n_stages - 1
    # initial carries must already be device-varying over the pipeline axis
    # so the scan carry type stays fixed (shard_map vma typing); under
    # check_vma=False pcast is unavailable and also unnecessary
    state = _pcast_varying(jnp.zeros(mb_shape, micro_all.dtype), axis)
    outputs = _pcast_varying(jnp.zeros_like(micro_all), axis)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (if still in range); other
        # stages consume what arrived from the left neighbour
        feed_idx = jnp.clip(t, 0, n_microbatches - 1)
        inp = jnp.where(stage == 0, micro_all[feed_idx], state)
        out = stage_fn(params_local, inp)
        # the last stage writes its finished microbatch (t - S + 1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
        write = (stage == n_stages - 1) & (t >= n_stages - 1)
        outputs = jnp.where(
            write,
            outputs.at[out_idx].set(out),
            outputs)
        # shift activations one stage to the right (ring permute; the
        # wrap-around value into stage 0 is ignored — it re-reads
        # micro_all)
        state = lax.ppermute(
            out, axis,
            [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(n_ticks))
    # every device carries a full `outputs` buffer but only the last
    # stage's is real; broadcast it (psum of masked buffer)
    return lax.psum(
        jnp.where(stage == n_stages - 1, outputs,
                  jnp.zeros_like(outputs)), axis)


def _pcast_varying(x, axis):
    try:
        return lax.pcast(x, axis, to="varying")
    except Exception:  # noqa: BLE001 — check_vma=False context: no-op
        return x


def pipeline_apply(stage_fn, stacked_params, x, *, mesh, n_microbatches,
                   axis="pp"):
    """Run ``x`` through S pipeline stages on the mesh's ``axis``.

    stage_fn(params_one_stage, microbatch) -> microbatch' — the same
    callable for every stage (homogeneous pipelining, the transformer
    case). ``stacked_params`` has a leading [S] axis; ``x`` has a leading
    batch axis that is split into ``n_microbatches``.

    Returns the output batch (same leading shape as x). Differentiable.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise MXNetError(
            f"batch {batch} not divisible by n_microbatches "
            f"{n_microbatches}")
    mb = batch // n_microbatches
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])

    def spmd(params_s, micro_all):
        # params_s: this stage's params (leading axis sliced to 1) — squeeze
        params_s = jax.tree.map(lambda a: a[0], params_s)
        return pipeline_local(stage_fn, params_s, micro_all, axis=axis,
                              n_stages=n_stages,
                              n_microbatches=n_microbatches)

    param_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P())
    out = fn(stacked_params, micro)
    return out.reshape((batch,) + out.shape[2:])


class Pipeline:
    """Convenience wrapper: hold stacked params + jit the pipelined forward.

    Example::

        def stage(params, x):
            return jnp.tanh(x @ params["w"] + params["b"])
        pp = Pipeline(stage, [stage0_params, ..., stage3_params],
                      mesh=make_mesh({"pp": 4}), n_microbatches=8)
        y = pp(x)
    """

    def __init__(self, stage_fn, per_stage_params, mesh, n_microbatches,
                 axis="pp"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_microbatches = n_microbatches
        stacked = stack_stage_params(per_stage_params)
        self.params = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, P(*([axis] + [None] * (a.ndim - 1))))), stacked)
        self._jitted = jax.jit(functools.partial(
            pipeline_apply, stage_fn, mesh=mesh,
            n_microbatches=n_microbatches, axis=axis))

    def __call__(self, x):
        return self._jitted(self.params, x)
