"""``mx.contrib`` — contrib namespace (reference: python/mxnet/contrib/).

amp and onnx live at their reference paths; quantization is here; the
contrib *operators* are under ``mx.nd.contrib``.
"""
from .. import amp  # noqa: F401  (reference path: mx.contrib.amp)
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401  (reference path: mx.contrib.text)
