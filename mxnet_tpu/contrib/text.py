"""``mx.contrib.text`` — vocabulary and token-embedding utilities.

Reference: python/mxnet/contrib/text/{utils,vocab,embedding}.py (the
word-embedding capability of SURVEY §2.4; GluonNLP's TokenEmbedding grew out
of this module). Embedding matrices live as one device-resident (V, D) array
— lookups are jnp takes (MXU-friendly gather), similarity queries one matmul.

Pretrained downloads (GloVe/fastText) need network access; in this offline
build ``create``/``get_pretrained_file_names`` raise with instructions to use
``CustomEmbedding`` on a local vector file instead.
"""
from __future__ import annotations

import collections
import io

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd

__all__ = ["count_tokens_from_str", "Vocabulary", "TokenEmbedding",
           "CustomEmbedding", "CompositeEmbedding", "create",
           "get_pretrained_file_names"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count tokens in a delimited string (reference contrib/text/utils.py)."""
    source_str = source_str.replace(seq_delim, token_delim)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(t for t in source_str.split(token_delim) if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with an unknown token and optional reserved tokens
    (reference contrib/text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        if reserved_tokens is not None:
            if len(set(reserved_tokens)) != len(reserved_tokens) or \
                    unknown_token in reserved_tokens:
                raise MXNetError("reserved_tokens must be unique and must "
                                 "not contain the unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) if reserved_tokens \
            else None
        self._idx_to_token = [unknown_token] + (self._reserved_tokens or [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for token, freq in pairs:
                if freq < min_freq:
                    continue
                if token not in self._token_to_idx:
                    self._token_to_idx[token] = len(self._idx_to_token)
                    self._idx_to_token.append(token)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Tokens -> indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"token index {i} out of range "
                                 f"[0, {len(self._idx_to_token)})")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


class TokenEmbedding(Vocabulary):
    """Vocabulary + a (V, D) vector table (reference contrib/text/embedding.py
    _TokenEmbedding). Lookup returns device arrays; unknown tokens get the
    init_unknown_vec row."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None   # NDArray (V, D)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding(self, file_like, elem_delim,
                        init_unknown_vec=nd.zeros):
        """Parse 'token v1 v2 ...' lines; tokens seen first win (reference
        loads in file order and warns on duplicates)."""
        vectors = {}
        vec_len = None
        # A first line of exactly two whole numbers *may* be a fastText
        # "count dim" header — but it may also be a legitimate 1-d vector
        # whose token is an integer string. Hold it until end of file:
        # it is a header iff treating it as a vector would disagree with
        # the file's vector length, or (1-d files) its first field equals
        # the number of following data rows, as a real count would.
        pending_header = None
        n_rows = 0
        for lineno, line in enumerate(file_like):
            parts = [p for p in line.rstrip().split(elem_delim) if p]
            if len(parts) < 2:
                continue
            token, elems = parts[0], parts[1:]
            if lineno == 0 and len(parts) == 2 and \
                    all(p.lstrip("-").isdigit() for p in parts):
                pending_header = (token, elems)
                continue
            n_rows += 1
            if vec_len is None:
                vec_len = len(elems)
            elif len(elems) != vec_len:
                raise MXNetError(
                    f"inconsistent vector length for token {token!r}: "
                    f"{len(elems)} vs {vec_len}")
            if token and token not in vectors:
                vectors[token] = _np.asarray([float(e) for e in elems],
                                             dtype=_np.float32)
        if pending_header is not None and vec_len in (None, 1) \
                and int(pending_header[0]) != n_rows:
            # not a credible header (its count field doesn't match the data
            # rows): it was a 1-d vector whose token is an integer string
            htok, helems = pending_header
            vec_len = 1
            if htok not in vectors:
                vectors[htok] = _np.asarray([float(e) for e in helems],
                                            dtype=_np.float32)
        if vec_len is None:
            raise MXNetError("no vectors found in the embedding file")
        self._vec_len = vec_len
        for token in vectors:
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
        table = _np.zeros((len(self), vec_len), dtype=_np.float32)
        table[0] = init_unknown_vec(shape=(vec_len,)).asnumpy()
        for token, vec in vectors.items():
            table[self._token_to_idx[token]] = vec
        self._idx_to_vec = nd.array(table)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            toks = [t if t in self._token_to_idx else t.lower()
                    for t in toks]
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = self._idx_to_vec[nd.array(idx, dtype="int32")]
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        for t in toks:
            if t not in self._token_to_idx or self._token_to_idx[t] == 0:
                raise MXNetError(f"token {t!r} is unknown; only tokens in "
                                 "the embedding can be updated")
        rows = nd.array([self._token_to_idx[t] for t in toks], dtype="int32")
        vals = new_vectors.reshape((len(toks), self._vec_len))
        # on-device scatter: no (V, D) host round-trip for a few-row update
        self._idx_to_vec[rows] = vals

    def most_similar(self, token, k=5):
        """k nearest tokens by cosine similarity — one (V,D)x(D,) matmul on
        device (the evaluation helper GluonNLP ships separately)."""
        import jax.numpy as jnp
        vec = self.get_vecs_by_tokens(token).data
        table = self._idx_to_vec.data
        norms = jnp.linalg.norm(table, axis=1) * jnp.linalg.norm(vec) + 1e-10
        sims = table @ vec / norms
        order = jnp.argsort(-sims)
        out = []
        for i in _np.asarray(order):
            t = self._idx_to_token[int(i)]
            if t != token and int(i) != 0:
                out.append((t, float(sims[int(i)])))
            if len(out) == k:
                break
        return out


class CustomEmbedding(TokenEmbedding):
    """Embedding from a local 'token v1 v2 ...' text file (reference
    contrib/text/embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        if vocabulary is not None:
            kwargs.setdefault("unknown_token", vocabulary.unknown_token)
        super().__init__(**kwargs)
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            self._load_embedding(f, elem_delim, init_unknown_vec)
        if vocabulary is not None:
            self._restrict_to(vocabulary)

    def _restrict_to(self, vocabulary):
        table = self._idx_to_vec.asnumpy()
        rows = _np.zeros((len(vocabulary), self._vec_len), _np.float32)
        for i, tok in enumerate(vocabulary.idx_to_token):
            j = self._token_to_idx.get(tok, 0)
            rows[i] = table[j]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._reserved_tokens = vocabulary.reserved_tokens
        self._unknown_token = vocabulary.unknown_token
        self._idx_to_vec = nd.array(rows)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference
    contrib/text/embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        super().__init__()
        embs = token_embeddings if isinstance(token_embeddings, list) \
            else [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._reserved_tokens = vocabulary.reserved_tokens
        self._unknown_token = vocabulary.unknown_token
        parts = [e.get_vecs_by_tokens(self._idx_to_token) for e in embs]
        self._idx_to_vec = nd.concat(*parts, dim=1)
        self._vec_len = self._idx_to_vec.shape[1]


def get_pretrained_file_names(embedding_name=None):
    raise MXNetError(
        "pretrained embedding downloads (glove/fasttext) need network "
        "access; this build is offline — load a local vector file with "
        "contrib.text.CustomEmbedding instead")


def create(embedding_name, **kwargs):
    raise MXNetError(
        "pretrained embedding downloads (glove/fasttext) need network "
        "access; this build is offline — load a local vector file with "
        "contrib.text.CustomEmbedding instead")
