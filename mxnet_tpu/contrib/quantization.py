"""INT8 post-training quantization.

Reference: python/mxnet/contrib/quantization.py (+ src/operator/quantization/
for the int8 kernels, SURVEY.md §2.2 "Quantization"): calibrate activation
ranges (naive min/max or KL-entropy), then run conv/fc in int8.

TPU-first: the int8 compute path is ``lax.dot_general(int8, int8,
preferred_element_type=int32)`` — XLA lowers this straight onto the MXU's
8-bit mode, so the quantized matmul is native, not emulated. Weights are
quantized per-output-channel symmetric; activations per-tensor affine from
the calibration thresholds.

Gluon-level API (the reference's 1.6-era `quantize_net`): walk the block
tree, swap `nn.Dense` / `nn.Conv2D` for quantized twins.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["quantize_net", "quantize_model", "calib_thresholds", "QuantizedDense",
           "QuantizedConv2D", "optimal_threshold_kl"]


def _quant_params_symmetric(w, axis=None):
    """Per-channel symmetric int8 scale for weights: s = max|w| / 127."""
    import jax.numpy as jnp
    from ..ops.quant_matmul import quantize_rtn_int8
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=axis is not None)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = quantize_rtn_int8(w, scale)
    return q, scale


def optimal_threshold_kl(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence calibration threshold (reference:
    _LayerHistogramCollector / _get_optimal_threshold): pick the clip
    threshold whose quantized distribution best matches the original.
    Pure numpy — runs on host once, offline."""
    hist = _np.asarray(hist, dtype=_np.float64)
    num_bins = len(hist)
    if num_bins < num_quantized_bins + 2:
        return float(hist_edges[-1])
    zero_bin = num_bins // 2
    best_kl, best_t = _np.inf, float(hist_edges[-1])
    # threshold sweep: symmetric windows growing from the center
    for i in range(num_quantized_bins // 2 + 1, num_bins // 2 + 1):
        lo, hi = zero_bin - i, zero_bin + i
        p = hist[lo:hi].copy()
        outliers = hist[:lo].sum() + hist[hi:].sum()
        if p.sum() == 0:
            continue
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        # quantize p into num_quantized_bins, then expand back
        factor = len(p) / num_quantized_bins
        q = _np.zeros_like(p)
        for j in range(num_quantized_bins):
            start = int(j * factor)
            stop = max(int((j + 1) * factor), start + 1)
            chunk = p[start:stop]
            nz = (chunk > 0).sum()
            if nz:
                q[start:stop] = _np.where(chunk > 0, chunk.sum() / nz, 0)
        pm = p / p.sum()
        qm = q / q.sum() if q.sum() else q
        mask = (pm > 0) & (qm > 0)
        if not mask.any():
            continue
        kl = float((pm[mask] * _np.log(pm[mask] / qm[mask])).sum())
        if kl < best_kl:
            best_kl = kl
            best_t = float(hist_edges[hi])
    return best_t


class _Collector:
    """Forward-hook activation range collector (naive or entropy mode)."""

    def __init__(self, mode="naive", num_bins=2001):
        self.mode = mode
        self.num_bins = num_bins
        self.ranges = {}      # block -> (min, max) or histogram
        self.hists = {}

    def hook(self, block):
        def pre_hook(blk, args):
            x = args[0]
            v = _np.asarray(x.asnumpy(), dtype=_np.float64)
            if self.mode == "naive":
                lo, hi = float(v.min()), float(v.max())
                old = self.ranges.get(blk)
                if old:
                    lo, hi = min(lo, old[0]), max(hi, old[1])
                self.ranges[blk] = (lo, hi)
            else:
                amax = float(_np.abs(v).max()) or 1e-8
                hist, edges = _np.histogram(v, bins=self.num_bins,
                                            range=(-amax, amax))
                old = self.hists.get(blk)
                if old is not None and len(old[0]) == len(hist) and \
                        old[1][-1] >= edges[-1]:
                    self.hists[blk] = (old[0] + hist, old[1])
                else:
                    self.hists[blk] = (hist, edges)
        return pre_hook

    def threshold(self, blk):
        if self.mode == "naive":
            lo, hi = self.ranges[blk]
            return max(abs(lo), abs(hi))
        hist, edges = self.hists[blk]
        return optimal_threshold_kl(hist, edges)


class QuantizedDense(HybridBlock):
    """int8 x int8 -> int32 Dense (reference: quantized_fully_connected)."""

    def __init__(self, dense, act_threshold, **kwargs):
        super().__init__(**kwargs)
        import jax.numpy as jnp
        w = dense.weight.data().data.astype(jnp.float32)
        self._qw, self._w_scale = _quant_params_symmetric(w, axis=1)
        self._bias = (dense.bias.data().data
                      if dense.bias is not None else None)
        self._act_scale = float(act_threshold) / 127.0
        self._units = dense._units if hasattr(dense, "_units") else w.shape[0]
        self._act_type = getattr(dense, "_act_type", None)
        self._flatten = getattr(dense, "_flatten", True)

    # public views for consumers that run the same int8 math outside
    # the block forward (the serving engine extracts these so its
    # compiled decode mirrors this layer op-for-op — docs/SERVING.md)
    @property
    def quantized_weight(self):
        """(units, in) int8 weight."""
        return self._qw

    @property
    def weight_scale(self):
        """(units, 1) per-output-channel dequant scale."""
        return self._w_scale

    @property
    def act_scale(self):
        """Scalar activation quant scale (threshold / 127)."""
        return self._act_scale

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray.ndarray import apply_nary
        qw, w_scale, a_scale = self._qw, self._w_scale, self._act_scale
        bias, act, flatten = self._bias, self._act_type, self._flatten

        def fn(d):
            # honor the wrapped Dense's flatten flag: flatten=False (sequence
            # models) quantizes over the last axis only, preserving leading
            # dims, exactly like the fp layer it replaces
            lead = d.shape[:1] if flatten else d.shape[:-1]
            flat = d.reshape(d.shape[0], -1) if flatten \
                else d.reshape(-1, d.shape[-1])
            from ..ops.quant_matmul import quantize_rtn_int8
            qx = quantize_rtn_int8(flat, a_scale)
            acc = lax.dot_general(
                qx, qw, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (a_scale * w_scale.reshape(1, -1))
            if bias is not None:
                out = out + bias
            if act == "relu":
                out = jnp.maximum(out, 0)
            return out.reshape(lead + (out.shape[-1],))

        return apply_nary(fn, [x], name="quantized_dense")


class QuantizedConv2D(HybridBlock):
    """int8 conv -> int32 accum (reference: quantized_conv)."""

    def __init__(self, conv, act_threshold, **kwargs):
        super().__init__(**kwargs)
        import jax.numpy as jnp
        w = conv.weight.data().data.astype(jnp.float32)   # (O, I, kh, kw)
        self._qw, self._w_scale = _quant_params_symmetric(
            w, axis=(1, 2, 3))
        self._bias = (conv.bias.data().data
                      if getattr(conv, "bias", None) is not None else None)
        self._act_scale = float(act_threshold) / 127.0
        self._kwargs = dict(getattr(conv, "_kwargs", {}))
        self._stride = self._kwargs.get("stride", (1, 1))
        self._pad = self._kwargs.get("pad", (0, 0))
        self._dilate = self._kwargs.get("dilate", (1, 1))
        self._groups = self._kwargs.get("num_group", 1)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray.ndarray import apply_nary
        qw, w_scale, a_scale = self._qw, self._w_scale, self._act_scale
        bias = self._bias
        stride, pad, dilate = self._stride, self._pad, self._dilate
        groups = self._groups

        def fn(d):
            from ..ops.quant_matmul import quantize_rtn_int8
            qx = quantize_rtn_int8(d, a_scale)
            dn = lax.conv_dimension_numbers(qx.shape, qw.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            acc = lax.conv_general_dilated(
                qx, qw, window_strides=tuple(stride),
                padding=[(p, p) for p in pad],
                rhs_dilation=tuple(dilate), dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            scale = (a_scale * w_scale.reshape(1, -1, 1, 1))
            out = acc.astype(jnp.float32) * scale
            if bias is not None:
                out = out + bias.reshape(1, -1, 1, 1)
            return out

        return apply_nary(fn, [x], name="quantized_conv")


def calib_thresholds(net, calib_data, calib_mode="naive", num_batches=10):
    """Run calibration forwards, return {block: threshold}."""
    from .. import _tape
    collector = _Collector(mode=("naive" if calib_mode == "naive"
                                 else "entropy"))
    targets = [b for b in _walk(net)
               if isinstance(b, (nn.Dense, nn.Conv2D))]
    handles = []
    for b in targets:
        h = collector.hook(b)
        b._forward_pre_hooks.append(h)
        handles.append((b, h))
    prev = _tape.set_training(False)
    try:
        for i, batch in enumerate(calib_data):
            if i >= num_batches:
                break
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            net(x)
    finally:
        _tape.set_training(prev)
        for b, h in handles:
            b._forward_pre_hooks.remove(h)
    return {b: collector.threshold(b) for b in targets
            if b in collector.ranges or b in collector.hists}


def _walk(block):
    yield block
    for child in block._children.values():
        yield from _walk(child)


def quantize_net(network, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=None,
                 num_calib_batches=10):
    """Quantize a Gluon net in place (reference: quantization.quantize_net).

    Replaces Dense/Conv2D children with int8 twins using calibrated
    activation thresholds. Blocks listed in `exclude_layers` (by name) and
    blocks never seen in calibration keep fp32.
    """
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError("only int8 quantization is supported on TPU "
                         "(uint8 has no MXU advantage)")
    if calib_data is None:
        raise MXNetError("calib_data is required (post-training "
                         "calibration)")
    exclude = set(exclude_layers or [])
    thresholds = calib_thresholds(network, calib_data, calib_mode,
                                  num_calib_batches)

    def convert(block):
        for name, child in list(block._children.items()):
            if child in thresholds and name not in exclude and \
                    child.weight._data is not None:
                if isinstance(child, nn.Dense):
                    q = QuantizedDense(child, thresholds[child])
                elif isinstance(child, nn.Conv2D):
                    q = QuantizedConv2D(child, thresholds[child])
                else:
                    continue
                block._children[name] = q
                if hasattr(block, name):
                    object.__setattr__(block, name, q)
            else:
                convert(child)
    convert(network)
    return network


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   calib_data=None, calib_mode="naive", quantized_dtype="int8",
                   **kwargs):
    """Reference quantization.quantize_model (Module-API PTQ): quantize a
    symbolic model. Here the symbolic graph is a facade over traced ops
    with no node-surgery pass, so Module-level PTQ routes through the
    Gluon path: wrap the symbol with SymbolBlock.imports / gluon, then
    call ``quantize_net`` (the reference's own successor API for Gluon
    models). Raises with that recipe rather than pretending to rewrite
    the graph."""
    raise MXNetError(
        "quantize_model: use quantize_net on a Gluon block instead — "
        "load the checkpoint into gluon (e.g. SymbolBlock/model_zoo), "
        "then contrib.quantization.quantize_net(net, calib_data=...). "
        "This build quantizes at the block level (int8 dot_general on "
        "the MXU), not by symbol-graph surgery.")
