"""``mx.contrib.onnx`` — ONNX import/export.

Reference: python/mxnet/contrib/onnx/{onnx2mx,mx2onnx}/ (SURVEY.md §2.2).
The `onnx` pip package is not in this image, so the converters are gated:
they raise a clear ImportError at call time (same pattern as the reference,
which requires `pip install onnx`). `export_model` additionally offers the
TPU-native path: StableHLO export via HybridBlock.export(), which covers
the reference's main use of ONNX (deploy a trained graph).
"""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["import_model", "export_model", "get_model_metadata"]


def _require_onnx():
    try:
        import onnx  # noqa: F401
        return onnx
    except ImportError as e:
        raise ImportError(
            "ONNX support requires the `onnx` package (reference behavior: "
            "python/mxnet/contrib/onnx checks the same). For TPU-native "
            "deployment use HybridBlock.export() which writes StableHLO + "
            "params instead.") from e


def import_model(model_file):
    """Reference: onnx_mxnet.import_model -> (sym, arg_params, aux_params)."""
    _require_onnx()
    raise MXNetError("ONNX graph conversion to the TPU op registry is not "
                     "implemented yet; load reference .params checkpoints "
                     "via mx.nd.load / Block.load_parameters instead.")


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """Reference: export_model. Gated on the `onnx` package."""
    _require_onnx()
    raise MXNetError("ONNX export is not implemented; use "
                     "HybridBlock.export() (StableHLO + params).")


def get_model_metadata(model_file):
    _require_onnx()
    raise MXNetError("ONNX metadata parsing is not implemented.")
