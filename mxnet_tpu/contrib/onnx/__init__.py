"""``mx.contrib.onnx`` — ONNX import/export, self-contained.

Reference: python/mxnet/contrib/onnx/{mx2onnx,onnx2mx} (SURVEY.md §2.2 row
45). The ``onnx`` pip package is not in this image, so the IR schema is
vendored (``onnx_ir.proto`` — field numbers match the public onnx.proto3,
so the files interoperate with any ONNX tooling) and compiled with protoc
to ``onnx_ir_pb2.py``. Covered op subset: the vision/MLP graph vocabulary
(Conv, Gemm, pooling, BatchNorm, activations, Softmax, Flatten, elemwise,
Concat, Reshape, Dropout) in both directions.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError

__all__ = ["import_model", "export_model", "get_model_metadata"]

_OPSET = 13


def _pb():
    from . import onnx_ir_pb2
    return onnx_ir_pb2


# ----------------------------------------------------------------------
# mx Symbol -> ONNX
# ----------------------------------------------------------------------

def _shape_attr(kw, key, default=None):
    v = kw.get(key, default)
    if v is None:
        return None
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),)


def _add_attr(node, name, value, pb):
    a = node.attribute.add()
    a.name = name
    if isinstance(value, float):
        a.type = pb.AttributeProto.FLOAT
        a.f = value
    elif isinstance(value, int):
        a.type = pb.AttributeProto.INT
        a.i = value
    elif isinstance(value, str):
        a.type = pb.AttributeProto.STRING
        a.s = value.encode()
    elif isinstance(value, (list, tuple)):
        a.type = pb.AttributeProto.INTS
        a.ints.extend(int(x) for x in value)
    else:
        raise MXNetError(f"unsupported attribute {name}={value!r}")


def _tensor(pb, name, arr):
    t = pb.TensorProto()
    t.name = name
    arr = _np.asarray(arr)
    t.dims.extend(arr.shape)
    if arr.dtype == _np.int64:
        t.data_type = pb.TensorProto.INT64
    elif arr.dtype == _np.int32:
        t.data_type = pb.TensorProto.INT32
    else:
        arr = arr.astype(_np.float32)
        t.data_type = pb.TensorProto.FLOAT
    t.raw_data = arr.tobytes()
    return t


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False):
    """Serialize a Symbol graph + params to an ONNX file.

    ``params``: dict name -> NDArray (Module.get_params()[0] style; an
    ``arg:``/``aux:`` prefix is stripped). ``input_shape``: the shape of
    the single data input (or dict name -> shape for several).
    Returns onnx_file_path. Reference: mx2onnx.export_model.
    """
    from ...symbol.symbol import Symbol, _collect_nodes
    pb = _pb()
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}

    model = pb.ModelProto()
    model.ir_version = 8
    model.producer_name = "mxnet_tpu"
    op = model.opset_import.add()
    op.domain = ""
    op.version = _OPSET
    g = model.graph
    g.name = getattr(sym, "_name", "network")

    seen = {}
    order = []
    for node in _collect_nodes(sym):
        if id(node) not in seen:
            seen[id(node)] = node
            order.append(node)

    out_name = {}     # id(Symbol) -> tensor name

    def name_of(s):
        if s._op is None and s._outputs is None:
            return s._name
        return out_name[id(s)]

    label_names = set()
    for s in order:
        if s._op in ("SoftmaxOutput", "LinearRegressionOutput",
                     "LogisticRegressionOutput", "MAERegressionOutput"):
            for a in s._args[1:]:
                if isinstance(a, Symbol) and a._op is None:
                    label_names.add(a._name)

    for s in order:
        if s._op is None:
            continue
        _emit_node(g, s, name_of, out_name, pb)

    used = set()
    for n in g.node:
        used.update(n.input)
    for pname, arr in params.items():
        if pname in used:
            g.initializer.append(_tensor(pb, pname, arr.asnumpy()
                                         if hasattr(arr, "asnumpy")
                                         else arr))
    init_names = {t.name for t in g.initializer}
    shapes = input_shape if isinstance(input_shape, dict) else None
    free_vars = [s._name for s in order
                 if s._op is None and s._outputs is None and
                 s._name not in init_names and
                 s._name not in label_names and s._name in used]
    if shapes is None and len(free_vars) > 1:
        # more than one non-param input with a single shape would stamp
        # the data shape onto e.g. BatchNorm moving stats missing from
        # `params` — refuse rather than write a broken file
        raise MXNetError(
            f"graph has several non-parameter inputs {free_vars} but one "
            "input_shape; pass a {name: shape} dict, or include aux "
            "params (moving_mean/var) in `params` — e.g. "
            "{**mod.get_params()[0], **mod.get_params()[1]}")
    for name in free_vars:
        vi = g.input.add()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = pb.TensorProto.FLOAT
        shp = shapes.get(name) if shapes else input_shape
        if shp is None:
            raise MXNetError(f"no shape given for graph input '{name}'")
        for d in shp:
            tt.shape.dim.add().dim_value = int(d)
    head = order[-1]
    out_vi = g.output.add()
    out_vi.name = name_of(head) if head._op else head._name
    out_vi.type.tensor_type.elem_type = pb.TensorProto.FLOAT

    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path


def _emit_node(g, s, name_of, out_name, pb):
    from ...symbol.symbol import Symbol
    kw = s._kwargs
    out = s._name
    out_name[id(s)] = out
    ins = [name_of(a) for a in s._args
           if isinstance(a, Symbol) and not (
               a._op is None and a._outputs is None and
               a._name.endswith("_label"))]

    def emit(op_type, inputs, outputs=None, **attrs):
        n = g.node.add()
        n.op_type = op_type
        n.name = out + "/" + op_type
        n.input.extend(inputs)
        n.output.extend(outputs or [out])
        for k, v in attrs.items():
            _add_attr(n, k, v, pb)
        return n

    op = s._op
    if op == "FullyConnected":
        data_in = ins[0]
        if kw.get("flatten", True):
            flat = out + "_flat"
            emit("Flatten", [data_in], [flat], axis=1)
            data_in = flat
        emit("Gemm", [data_in] + ins[1:], alpha=1.0, beta=1.0,
             transA=0, transB=1)
    elif op == "Convolution":
        kernel = _shape_attr(kw, "kernel")
        stride = _shape_attr(kw, "stride", (1,) * len(kernel))
        pad = _shape_attr(kw, "pad", (0,) * len(kernel))
        dilate = _shape_attr(kw, "dilate", (1,) * len(kernel))
        emit("Conv", ins, kernel_shape=kernel, strides=stride,
             pads=list(pad) * 2, dilations=dilate,
             group=int(kw.get("num_group", 1)))
    elif op == "Activation":
        act = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "softrelu": "Softplus"}.get(kw.get("act_type", "relu"))
        if act is None:
            raise MXNetError(f"no ONNX mapping for activation "
                             f"{kw.get('act_type')!r}")
        emit(act, ins)
    elif op == "LeakyReLU":
        emit("LeakyRelu", ins, alpha=float(kw.get("slope", 0.25)))
    elif op == "Pooling":
        kernel = _shape_attr(kw, "kernel", (2, 2))
        stride = _shape_attr(kw, "stride", kernel)
        pad = _shape_attr(kw, "pad", (0,) * len(kernel))
        ptype = kw.get("pool_type", "max")
        if kw.get("global_pool", False):
            emit("GlobalMaxPool" if ptype == "max"
                 else "GlobalAveragePool", ins)
        else:
            emit("MaxPool" if ptype == "max" else "AveragePool", ins,
                 kernel_shape=kernel, strides=stride, pads=list(pad) * 2)
    elif op in ("SoftmaxOutput", "softmax"):
        emit("Softmax", ins[:1], axis=-1)
    elif op in ("LinearRegressionOutput", "MAERegressionOutput"):
        emit("Identity", ins[:1])
    elif op == "LogisticRegressionOutput":
        emit("Sigmoid", ins[:1])
    elif op == "BatchNorm":
        emit("BatchNormalization", ins,
             epsilon=float(kw.get("eps", 1e-5)),
             momentum=float(kw.get("momentum", 0.9)))
    elif op == "Flatten":
        emit("Flatten", ins, axis=1)
    elif op == "Dropout":
        emit("Dropout", ins)
    elif op in ("elemwise_add", "broadcast_add", "_plus", "_Plus"):
        emit("Add", ins)
    elif op in ("elemwise_mul", "broadcast_mul", "_mul"):
        emit("Mul", ins)
    elif op == "Concat":
        emit("Concat", ins, axis=int(kw.get("dim", 1)))
    elif op == "Reshape":
        shape = _shape_attr(kw, "shape")
        shape_name = out + "_shape"
        g.initializer.append(_tensor(pb, shape_name,
                                     _np.asarray(shape, _np.int64)))
        emit("Reshape", ins + [shape_name])
    elif op == "dot":
        emit("MatMul", ins)
    elif op == "identity":
        emit("Identity", ins)
    else:
        raise MXNetError(
            f"op '{op}' has no ONNX export mapping (supported: the "
            "vision/MLP subset — see contrib/onnx docstring)")


# ----------------------------------------------------------------------
# ONNX -> mx Symbol
# ----------------------------------------------------------------------

def import_model(model_file):
    """Parse an ONNX file into (sym, arg_params, aux_params).
    Reference: onnx2mx.import_model."""
    pb = _pb()
    model = pb.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph

    from ... import symbol as mx_sym
    from ...ndarray.ndarray import array as nd_array

    tensors = {}      # tensor name -> Symbol
    params_np = {t.name: _tensor_to_np(t, pb) for t in g.initializer}
    for vi in g.input:
        if vi.name not in params_np:
            tensors[vi.name] = mx_sym.var(vi.name)
    for name in params_np:
        tensors[name] = mx_sym.var(name)

    fresh = _make_fresh()
    for node in g.node:
        _import_node(node, tensors, params_np, mx_sym, fresh, pb)

    out = tensors[g.output[0].name] if g.output else \
        tensors[list(tensors)[-1]]
    arg_params, aux_params = {}, {}
    for name, arr in params_np.items():
        if arr.dtype == _np.int64:
            continue    # shape tensors, consumed at graph build
        nd = nd_array(arr)
        if "moving_" in name or "running_" in name or ".mean" in name \
                or ".var" in name:
            aux_params[name] = nd
        else:
            arg_params[name] = nd
    return out, arg_params, aux_params


def _tensor_to_np(t, pb):
    dt = {pb.TensorProto.FLOAT: _np.float32,
          pb.TensorProto.INT64: _np.int64,
          pb.TensorProto.INT32: _np.int32,
          pb.TensorProto.DOUBLE: _np.float64}.get(t.data_type)
    if dt is None:
        raise MXNetError(f"unsupported ONNX tensor dtype {t.data_type}")
    shape = tuple(t.dims)
    if t.raw_data:
        return _np.frombuffer(t.raw_data, dt).reshape(shape).copy()
    if t.float_data:
        return _np.asarray(t.float_data, dt).reshape(shape)
    if t.int64_data:
        return _np.asarray(t.int64_data, dt).reshape(shape)
    if t.int32_data:
        return _np.asarray(t.int32_data, dt).reshape(shape)
    return _np.zeros(shape, dt)


def _attrs(node):
    pb = _pb()
    out = {}
    for a in node.attribute:
        if a.type == pb.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == pb.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = tuple(int(x) for x in a.ints)
    return out


def _sym_pads(at, op):
    """ONNX pads [b1..bn, e1..en] -> symmetric (p1..pn); raise if begin
    and end halves differ (a silent truncation changes output shapes)."""
    pads = at.get("pads")
    if not pads:
        return None
    half = len(pads) // 2
    begin, end = tuple(pads[:half]), tuple(pads[half:])
    if begin != end:
        raise MXNetError(
            f"ONNX {op} with asymmetric pads {pads} is not supported "
            "(begin half must equal end half)")
    return begin


def _import_node(node, tensors, params_np, mx_sym, fresh, pb):
    at = _attrs(node)
    ins = [tensors[i] for i in node.input if i in tensors]
    out = node.output[0]
    op = node.op_type
    base = node.name or out

    def put(sym):
        tensors[out] = sym

    if op == "Gemm":
        # only the FullyConnected-shaped Gemm (y = x @ W.T + b) maps; a
        # silent mis-map would return transposed-weight garbage
        if at.get("transA", 0) or not at.get("transB", 1) or \
                at.get("alpha", 1.0) != 1.0 or at.get("beta", 1.0) != 1.0:
            raise MXNetError(
                f"ONNX Gemm with transA={at.get('transA', 0)} "
                f"transB={at.get('transB', 1)} alpha={at.get('alpha', 1.0)} "
                f"beta={at.get('beta', 1.0)} is not supported (only the "
                "FullyConnected form transA=0 transB=1 alpha=beta=1)")
        w = params_np[node.input[1]]
        put(mx_sym.FullyConnected(*ins, num_hidden=int(w.shape[0]),
                                  no_bias=len(ins) < 3,
                                  name=fresh(base)))
    elif op == "MatMul":
        put(mx_sym.dot(*ins, name=fresh(base)))
    elif op == "Conv":
        w = params_np[node.input[1]]
        pad = _sym_pads(at, op)
        put(mx_sym.Convolution(
            *ins, num_filter=int(w.shape[0]),
            kernel=at.get("kernel_shape", tuple(w.shape[2:])),
            stride=at.get("strides", (1,) * len(w.shape[2:])),
            pad=pad if pad else (0,) * len(w.shape[2:]),
            num_group=int(at.get("group", 1)),
            no_bias=len(ins) < 3, name=fresh(base)))
    elif op in ("Relu", "Sigmoid", "Tanh", "Softplus"):
        act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
               "Softplus": "softrelu"}[op]
        put(mx_sym.Activation(ins[0], act_type=act, name=fresh(base)))
    elif op == "LeakyRelu":
        put(mx_sym.LeakyReLU(ins[0], slope=at.get("alpha", 0.01),
                             name=fresh(base)))
    elif op in ("MaxPool", "AveragePool"):
        kernel = at["kernel_shape"]
        pad = _sym_pads(at, op)
        put(mx_sym.Pooling(
            ins[0], kernel=kernel,
            stride=at.get("strides", kernel),
            pad=pad if pad else (0,) * len(kernel),
            pool_type="max" if op == "MaxPool" else "avg",
            name=fresh(base)))
    elif op in ("GlobalMaxPool", "GlobalAveragePool"):
        put(mx_sym.Pooling(
            ins[0], global_pool=True, kernel=(1, 1),
            pool_type="max" if op == "GlobalMaxPool" else "avg",
            name=fresh(base)))
    elif op == "BatchNormalization":
        put(mx_sym.BatchNorm(*ins, eps=at.get("epsilon", 1e-5),
                             momentum=at.get("momentum", 0.9),
                             name=fresh(base)))
    elif op == "Softmax":
        put(mx_sym.softmax(ins[0], name=fresh(base)))
    elif op == "Flatten":
        put(mx_sym.Flatten(ins[0], name=fresh(base)))
    elif op == "Dropout":
        put(mx_sym.Dropout(ins[0], name=fresh(base)))
    elif op == "Add":
        put(ins[0] + ins[1])
    elif op == "Mul":
        put(ins[0] * ins[1])
    elif op == "Concat":
        put(mx_sym.Concat(*ins, dim=int(at.get("axis", 1)),
                          name=fresh(base)))
    elif op == "Reshape":
        shape = tuple(int(x) for x in params_np[node.input[1]])
        put(mx_sym.Reshape(ins[0], shape=shape, name=fresh(base)))
    elif op == "Identity":
        put(ins[0])
    else:
        raise MXNetError(
            f"ONNX op '{op}' has no import mapping (supported: the "
            "vision/MLP subset — see contrib/onnx docstring)")


def _make_fresh():
    """Per-import name deduper — deterministic across calls (a module
    global would rename nodes on every re-import of the same file)."""
    counter = {}

    def fresh(base):
        base = base.replace("/", "_").replace(":", "_")
        i = counter.get(base, 0)
        counter[base] = i + 1
        return base if i == 0 else f"{base}_{i}"
    return fresh


def get_model_metadata(model_file):
    """Reference: get_model_metadata -> {input_tensor_data,
    output_tensor_data}."""
    pb = _pb()
    model = pb.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def dims(vi):
        return tuple(d.dim_value for d in vi.type.tensor_type.shape.dim)

    return {
        "input_tensor_data": [(vi.name, dims(vi)) for vi in g.input
                              if vi.name not in inits],
        "output_tensor_data": [(vi.name, dims(vi)) for vi in g.output],
    }
