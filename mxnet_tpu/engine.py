"""``mx.engine`` — dependency-engine control shims.

Reference: python/mxnet/engine.py (bulk/set_bulk_size) over the threaded
engine (SURVEY §2.1 row 1). The TPU rebuild has no threaded engine — XLA
async dispatch plays that role — so these controls are accepted for API
compatibility and mapped to their closest real effect:

- ``set_bulk_size`` is a no-op returning the previous value (XLA fuses the
  whole jitted program; there is no op-bulking knob to turn).
- ``bulk`` is a null context manager.
- The debug switch the reference exposes as MXNET_ENGINE_TYPE=NaiveEngine
  (serialize everything) maps to MXTPU_EAGER=1 — disable hybridize jit and
  run op-by-op; see base.py feature flags.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 15   # reference default MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN


def set_bulk_size(size):
    """Accepted for compatibility; returns the previous setting. XLA fusion
    subsumes engine op-bulking (SURVEY §2.1 disposition)."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """Reference mx.engine.bulk(size): batch engine pushes. No-op here —
    everything inside a hybridized block is already one XLA program."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
