"""``mx.util`` — misc utilities (reference: python/mxnet/util.py)."""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "set_np", "reset_np", "is_np_array", "use_np",
           "set_np_shape", "is_np_shape", "use_np_shape", "use_np_array",
           "getenv", "setenv", "get_gpu_count", "get_gpu_memory"]

_NP_ARRAY = False
_NP_SHAPE = False


def makedirs(d):
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def set_np_shape(active=True):
    """Reference util.set_np_shape: zero-dim/zero-size shape semantics.
    jax.numpy always HAS them; the flag tracks the user intent so
    is_np_shape() answers like the reference. Returns the previous
    setting."""
    global _NP_SHAPE
    prev = _NP_SHAPE
    _NP_SHAPE = bool(active)
    return prev


def is_np_shape():
    return _NP_SHAPE


def set_np(shape=True, array=True):
    """numpy-semantics switch. jax.numpy is already numpy-semantics, so
    this only maintains the two flags — linked like the reference, which
    forbids array semantics without shape semantics."""
    if array and not shape:
        raise ValueError(
            "np-array semantics require np-shape semantics "
            "(reference util.set_np raises the same)")
    global _NP_ARRAY
    set_np_shape(shape)
    _NP_ARRAY = array


def reset_np():
    set_np(shape=False, array=False)


def is_np_array():
    return _NP_ARRAY


def use_np(func):
    """Run func under full numpy semantics (shape + array), restoring the
    previous flags after (reference @use_np = use_np_shape + use_np_array)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        global _NP_ARRAY
        prev_array, prev_shape = _NP_ARRAY, _NP_SHAPE
        set_np()
        try:
            return func(*args, **kwargs)
        finally:
            set_np_shape(prev_shape)
            _NP_ARRAY = prev_array
    return wrapper


def use_np_shape(func):
    """Reference decorator: run func under numpy shape semantics."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        prev = set_np_shape(True)
        try:
            return func(*args, **kwargs)
        finally:
            set_np_shape(prev)
    return wrapper


# array semantics imply shape semantics here exactly as in @use_np
use_np_array = use_np


def getenv(name):
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = value


def get_gpu_count():
    from .context import num_tpus
    return num_tpus()


def get_gpu_memory(dev_id=0):
    import jax
    try:
        stats = jax.devices()[dev_id].memory_stats()
        return stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0)
    except Exception:
        return 0, 0
