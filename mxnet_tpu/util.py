"""``mx.util`` — misc utilities (reference: python/mxnet/util.py)."""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "set_np", "reset_np", "is_np_array", "use_np",
           "getenv", "setenv", "get_gpu_count", "get_gpu_memory"]

_NP_ARRAY = False


def makedirs(d):
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def set_np(shape=True, array=True):
    """numpy-semantics switch. jax.numpy is already numpy-semantics, so this
    only flips the flag consulted by is_np_array()."""
    global _NP_ARRAY
    _NP_ARRAY = array


def reset_np():
    global _NP_ARRAY
    _NP_ARRAY = False


def is_np_array():
    return _NP_ARRAY


def use_np(func):
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        prev = _NP_ARRAY
        set_np()
        try:
            return func(*args, **kwargs)
        finally:
            set_np(array=prev)
    return wrapper


def getenv(name):
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = value


def get_gpu_count():
    from .context import num_tpus
    return num_tpus()


def get_gpu_memory(dev_id=0):
    import jax
    try:
        stats = jax.devices()[dev_id].memory_stats()
        return stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0)
    except Exception:
        return 0, 0
