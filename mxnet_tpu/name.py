"""``mx.name`` — auto-naming manager for symbols.

Reference: python/mxnet/name.py (NameManager/Prefix). Symbol ops created
without an explicit ``name=`` consult the innermost active manager; the
default manager numbers per-op ("convolution0"), Prefix prepends a string —
exactly the reference behavior the Module/viz layers rely on for stable
param names.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Scope manager that turns op-type hints into unique names."""

    _state = threading.local()

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        i = self._counter.get(hint, 0)
        self._counter[hint] = i + 1
        return f"{hint}{i}"

    def __enter__(self):
        stack = self._stack()
        stack.append(self)
        return self

    def __exit__(self, *exc):
        self._stack().pop()
        return False

    @classmethod
    def _stack(cls):
        if not hasattr(cls._state, "stack"):
            cls._state.stack = []
        return cls._state.stack


class Prefix(NameManager):
    """``with mx.name.Prefix('mynet_'):`` — prefix every auto name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


def current():
    stack = NameManager._stack()
    return stack[-1] if stack else None
