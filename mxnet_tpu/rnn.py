"""``mx.rnn`` — legacy (pre-Gluon) RNN namespace.

Reference: python/mxnet/rnn/ (rnn_cell.py with symbolic cells,
io.py with BucketSentenceIter, rnn.py with checkpoint helpers) — the API
the reference's ``example/rnn`` bucketing LSTM uses.

TPU-native disposition (SURVEY.md §3/§7 "BucketingModule + gluon.rnn
unrolling"): the cell classes ARE the gluon cells (same math, tape/jit
aware) re-exported under their legacy names; ``BucketSentenceIter``
feeds ``BucketingModule`` exactly like the reference's. The legacy
symbolic ``sym_gen``-style flow maps to BucketingModule whose
``sym_gen`` builds through ``mx.sym`` or a gluon block per bucket.
Checkpoint helpers delegate to the shared NDArray container
(``mx.nd.save``/``load`` read AND write the reference's .params format).
"""
from __future__ import annotations

import bisect

import numpy as _np

from .base import MXNetError
from .gluon.rnn import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                        BidirectionalCell, DropoutCell, ResidualCell,
                        ZoneoutCell)
from . import io as _io
from . import ndarray as _nd

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ResidualCell",
           "ZoneoutCell", "BucketSentenceIter",
           "save_rnn_checkpoint", "load_rnn_checkpoint"]


class BucketSentenceIter(_io.DataIter):
    """Bucketed variable-length sequence iterator (reference
    python/mxnet/rnn/io.py): sentences are padded up to the smallest
    bucket that fits and batched per bucket; each batch carries
    ``bucket_key`` so BucketingModule switches executors."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            # reference default: one bucket per observed length with enough
            # sentences to fill at least one batch
            counts = {}
            for s in sentences:
                counts[len(s)] = counts.get(len(s), 0) + 1
            buckets = sorted(k for k, n in counts.items()
                             if n >= batch_size) or \
                [max(len(s) for s in sentences)]
        self.buckets = sorted(buckets)
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.batch_size = batch_size
        self._dtype = dtype
        if layout not in ("NT", "TN"):
            raise MXNetError(f"layout must be 'NT' or 'TN', got {layout!r}")
        self.layout = layout          # TN = time-major (reference example)

        self._data = [[] for _ in self.buckets]
        n_discarded = 0
        for s in sentences:
            i = bisect.bisect_left(self.buckets, len(s))
            if i >= len(self.buckets):
                n_discarded += 1      # longer than the largest bucket
                continue
            # rows are built in the REQUESTED dtype: a float32 staging
            # buffer would round token ids >= 2^24
            row = _np.full((self.buckets[i],), invalid_label,
                           _np.dtype(dtype))
            row[:len(s)] = s
            self._data[i].append(row)
        if n_discarded:
            import logging
            logging.warning(
                "BucketSentenceIter: discarded %d sentences longer than "
                "the largest bucket (%d)", n_discarded, max(self.buckets))
        self._data = [_np.asarray(rows, dtype=_np.dtype(dtype))
                      for rows in self._data]
        self.default_bucket_key = max(self.buckets)
        self._plan = []               # (bucket_idx, start) batches
        self.reset()

    def _shape(self, t):
        return (self.batch_size, t) if self.layout == "NT" \
            else (t, self.batch_size)

    @property
    def provide_data(self):
        return [_io.DataDesc(self.data_name,
                             self._shape(self.default_bucket_key),
                             self._dtype, layout=self.layout)]

    @property
    def provide_label(self):
        return [_io.DataDesc(self.label_name,
                             self._shape(self.default_bucket_key),
                             self._dtype, layout=self.layout)]

    def reset(self):
        self._plan = []
        for i, rows in enumerate(self._data):
            for start in range(0, len(rows) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((i, start))
        _np.random.shuffle(self._plan)
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        i, start = self._plan[self._cursor]
        self._cursor += 1
        rows = self._data[i][start:start + self.batch_size]
        # language-model convention: label is data shifted left one step
        label = _np.full_like(rows, self.invalid_label)
        label[:, :-1] = rows[:, 1:]
        if self.layout == "TN":
            rows, label = rows.T, label.T
        return _io.DataBatch(
            data=[_nd.array(rows, dtype=self._dtype)],
            label=[_nd.array(label, dtype=self._dtype)],
            bucket_key=self.buckets[i],
            provide_data=[_io.DataDesc(
                self.data_name, self._shape(self.buckets[i]),
                self._dtype, layout=self.layout)],
            provide_label=[_io.DataDesc(
                self.label_name, self._shape(self.buckets[i]),
                self._dtype, layout=self.layout)])


def save_rnn_checkpoint(cells, prefix, epoch, symbol=None, arg_params=None,
                        aux_params=None):
    """Reference rnn.save_rnn_checkpoint: the cells' params merged into
    the checkpoint alongside symbol/arg/aux — delegates to the shared
    module.save_checkpoint_arrays so nothing passed is dropped."""
    from .module.module import save_checkpoint_arrays
    params = dict(arg_params or {})
    for cell in cells if isinstance(cells, (list, tuple)) else [cells]:
        for name, p in cell.collect_params().items():
            params[name] = p.data()
    save_checkpoint_arrays(prefix, epoch, symbol, params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Reference rnn.load_rnn_checkpoint: restore cell params in place and
    return (symbol, arg_params, aux_params) like mx.model.load_checkpoint
    (the resume-training pattern unpacks the triple)."""
    from .module.module import load_checkpoint
    symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
    for cell in cells if isinstance(cells, (list, tuple)) else [cells]:
        for name, p in cell.collect_params().items():
            if name not in arg_params:
                raise MXNetError(f"parameter {name} not in checkpoint "
                                 f"{prefix}-{epoch:04d}.params")
            p.set_data(arg_params[name])
    return symbol, arg_params, aux_params
