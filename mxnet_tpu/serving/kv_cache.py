"""Paged KV cache: block-table indexed, per-sequence alloc/free.

The serving decode batch holds ``max_batch`` sequences of wildly
different lengths; a dense (B, max_seq, ...) cache would reserve
worst-case HBM for every slot.  Instead K/V live in a shared pool of
fixed-size blocks (the vLLM PagedAttention layout, here sized for the
TPU serving engine): each sequence owns an ordered list of physical
block ids (its *block table*), blocks are handed out on demand as the
sequence grows and returned to the free list the moment the sequence
finishes — so cache memory tracks the LIVE token count, not
max_batch x max_seq.

Device side the pool is two jnp arrays of shape
``(layers, num_blocks, block_size, kv_heads, head_dim)``; the compiled
prefill/decode graphs take them as donated arguments and return the
updated pool (functional update, carry donated like PR 6's
``step_multi``), while this class keeps the HOST truth: the free list,
per-slot block tables and lengths.  Physical block 0 is reserved as the
null block — block-table padding and inactive batch rows point at it so
every gather/scatter index stays in range; its contents are garbage by
design and masked out of every attention (position mask).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError

__all__ = ["PagedKVCache"]


class PagedKVCache:
    """Block-pooled KV storage for one model.

    Parameters
    ----------
    num_layers, num_kv_heads, head_dim : model geometry.
    num_blocks : total physical blocks in the pool INCLUDING the
        reserved null block 0.
    block_size : tokens per block (power of two; decode context buckets
        are multiples of it).
    max_batch : decode slots (sequences resident at once).
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, num_blocks=64,
                 block_size=16, max_batch=4, dtype=None):
        import jax.numpy as jnp
        if block_size < 1 or (block_size & (block_size - 1)):
            raise MXNetError("block_size must be a power of two, got "
                             f"{block_size}")
        if num_blocks < 2:
            raise MXNetError("num_blocks must be >= 2 (block 0 is the "
                             "reserved null block)")
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.max_batch = max_batch
        self.dtype = dtype or jnp.float32
        shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
        self.k_pool = jnp.zeros(shape, self.dtype)
        self.v_pool = jnp.zeros(shape, self.dtype)
        # LIFO free list: freshly freed blocks are reused first (warm)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._tables = {}        # slot -> [physical block ids]
        self._lens = {}          # slot -> tokens stored
        self.alloc_failures = 0  # pool-exhausted alloc attempts (stats)

    # -- allocation ------------------------------------------------------

    @property
    def num_free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        return (self.num_blocks - 1) - len(self._free)

    def utilization(self):
        """Fraction of allocatable blocks currently owned by sequences."""
        total = self.num_blocks - 1
        return self.blocks_in_use / total if total else 0.0

    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` positions."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, slot, n_tokens):
        """Give ``slot`` enough blocks for ``n_tokens`` positions.
        Returns False (and allocates nothing) when the pool can't cover
        the request — the scheduler then leaves the request queued."""
        if slot in self._tables:
            raise MXNetError(f"slot {slot} already allocated; free() first")
        need = self.blocks_for(n_tokens)
        if need > len(self._free):
            self.alloc_failures += 1
            return False
        self._tables[slot] = [self._free.pop() for _ in range(need)]
        self._lens[slot] = 0
        return True

    def ensure(self, slot, pos):
        """Grow ``slot``'s table to cover position ``pos`` (0-based).
        Returns False when the pool is exhausted (caller may evict or
        stall the sequence)."""
        table = self._tables[slot]
        need = self.blocks_for(pos + 1) - len(table)
        if need <= 0:
            return True
        if need > len(self._free):
            self.alloc_failures += 1
            return False
        table.extend(self._free.pop() for _ in range(need))
        return True

    def trim(self, slot, n_tokens):
        """Shrink ``slot``'s table to exactly cover ``n_tokens``
        positions, returning the tail blocks to the pool (prefill
        allocates for the padded BUCKET; the pad tail is garbage by
        construction — decode overwrites a position before ever reading
        it — so the blocks can be handed to other sequences now)."""
        table = self._tables[slot]
        keep = self.blocks_for(n_tokens)
        while len(table) > keep:
            self._free.append(table.pop())

    def free(self, slot):
        """Return all of ``slot``'s blocks to the pool."""
        for blk in self._tables.pop(slot, ()):
            self._free.append(blk)
        self._lens.pop(slot, None)

    def set_len(self, slot, n):
        self._lens[slot] = int(n)

    def seq_len(self, slot):
        return self._lens.get(slot, 0)

    def table(self, slot):
        return list(self._tables.get(slot, ()))

    # -- device-facing views --------------------------------------------

    def table_array(self, slots, width):
        """(len(slots), width) int32 block-table matrix for the compiled
        decode step: row i is ``slots[i]``'s table, padded with the null
        block; a ``None`` slot (inactive batch row) is all-null."""
        out = _np.zeros((len(slots), width), _np.int32)
        for i, slot in enumerate(slots):
            if slot is None:
                continue
            t = self._tables.get(slot, ())
            if len(t) > width:
                raise MXNetError(
                    f"slot {slot} holds {len(t)} blocks but the decode "
                    f"bucket only gathers {width}; bucket too small")
            out[i, :len(t)] = t[:width]
        return out

    def update_pools(self, k_pool, v_pool):
        """Swap in the pools returned by a compiled (donated) step."""
        self.k_pool = k_pool
        self.v_pool = v_pool

    def stats(self):
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "blocks_in_use": self.blocks_in_use,
                "utilization": round(self.utilization(), 4),
                "alloc_failures": self.alloc_failures,
                "sequences": len(self._tables)}
