"""Paged KV cache: block-table indexed, per-sequence alloc/free, with
per-block refcounts for copy-on-write prefix sharing.

The serving decode batch holds ``max_batch`` sequences of wildly
different lengths; a dense (B, max_seq, ...) cache would reserve
worst-case HBM for every slot.  Instead K/V live in a shared pool of
fixed-size blocks (the vLLM PagedAttention layout, here sized for the
TPU serving engine): each sequence owns an ordered list of physical
block ids (its *block table*), blocks are handed out on demand as the
sequence grows and returned to the free list the moment the sequence
finishes — so cache memory tracks the LIVE token count, not
max_batch x max_seq.

Device side the pool is two jnp arrays of shape
``(layers, num_blocks, block_size, kv_heads, head_dim)``; the compiled
prefill/decode graphs take them as donated arguments and return the
updated pool (functional update, carry donated like PR 6's
``step_multi``), while this class keeps the HOST truth: the free list,
per-slot block tables, lengths, and per-block REFCOUNTS.  Physical
block 0 is reserved as the null block — block-table padding and
inactive batch rows point at it so every gather/scatter index stays in
range; its contents are garbage by design and masked out of every
attention (position mask).

Refcounts (ISSUE 12): a block freshly popped from the free list has
refcount 1 (its owning slot).  ``fork``/``adopt`` hand the SAME
physical blocks to another holder and bump the count — this is how the
prefix cache shares one prefilled system prompt across every request
that starts with it.  A shared block is immutable: before the engine
scatters K/V into a position whose block has refcount > 1,
``prepare_write`` allocates a fresh block and the engine copies the
old contents device-side (copy-on-write; the writer pays, every other
holder keeps the original bits).  ``free``/``trim`` only DECREMENT; a
block returns to the free list exactly when its count hits 0, so
eviction can never reclaim memory another sequence still reads.
Refcount violations (double free, underflow) raise the typed
:class:`DoubleFreeError` instead of corrupting the free list.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..lint import donation as _donation

__all__ = ["PagedKVCache", "DoubleFreeError", "HandoffError"]


class DoubleFreeError(MXNetError):
    """A block refcount went below zero or a slot was freed twice —
    the host-side block accounting is corrupt and continuing would
    hand one sequence's KV memory to another."""


class HandoffError(MXNetError):
    """A paged-KV block handoff between replicas violated the
    ownership protocol (ISSUE 18 disaggregated prefill/decode): the
    adopting side must take its reference BEFORE the releasing side
    drops its own (adopt-then-release), both sides must share one
    physical pool, and every handed-off block must carry >= 2 holders
    at the instant of release.  Anything else would let a decode
    replica read blocks the free list already recycled."""


class PagedKVCache:
    """Block-pooled KV storage for one model.

    Parameters
    ----------
    num_layers, num_kv_heads, head_dim : model geometry.
    num_blocks : total physical blocks in the pool INCLUDING the
        reserved null block 0.
    block_size : tokens per block (power of two; decode context buckets
        are multiples of it).
    max_batch : decode slots (sequences resident at once).
    sharding : optional ``jax.sharding.Sharding`` the pools are placed
        with at rest (ISSUE 18 tp serving shards the kv-head axis of
        the engine's submesh); None keeps single-device pools.
    kv_dtype : low-precision STORAGE mode (ISSUE 20): ``"fp8"`` stores
        float8_e4m3fn codes plus per-token-row amax scale arrays
        ``k_scale``/``v_scale`` of shape ``(layers, num_blocks,
        block_size)`` riding alongside the pools; ``"bf16"`` stores
        bfloat16 codes (no scales); ``"fp32"``/unset is today's f32
        pool, bitwise.  ``None`` reads ``MXTPU_KV_DTYPE``.  The HOST
        accounting (refcounts, CoW, handoff) is dtype-blind — only the
        device arrays change.
    """

    def __init__(self, num_layers, num_kv_heads, head_dim, num_blocks=64,
                 block_size=16, max_batch=4, dtype=None, sharding=None,
                 kv_dtype=None):
        import jax.numpy as jnp
        from ..ops import quant_kv as _qkv
        if block_size < 1 or (block_size & (block_size - 1)):
            raise MXNetError("block_size must be a power of two, got "
                             f"{block_size}")
        if num_blocks < 2:
            raise MXNetError("num_blocks must be >= 2 (block 0 is the "
                             "reserved null block)")
        self.num_layers = num_layers
        self.num_kv_heads = num_kv_heads
        self.head_dim = head_dim
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.max_batch = max_batch
        self.kv_dtype = _qkv.resolve_kv_dtype(kv_dtype)
        if self.kv_dtype is not None:
            self.dtype = _qkv.kv_pool_dtype(self.kv_dtype)
        else:
            self.dtype = dtype or jnp.float32
        shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
        self.sharding = sharding
        if sharding is not None:
            import jax
            self.k_pool = jax.device_put(jnp.zeros(shape, self.dtype),
                                         sharding)
            self.v_pool = jax.device_put(jnp.zeros(shape, self.dtype),
                                         sharding)
        else:
            self.k_pool = jnp.zeros(shape, self.dtype)
            self.v_pool = jnp.zeros(shape, self.dtype)
        # fp8 scale rows: ONE f32 amax scale per written token row,
        # indexed exactly like the pools' (layer, block, offset) —
        # scales ride the same donate/update_pools round-trip
        self.k_scale = self.v_scale = None
        if _qkv.kv_has_scales(self.kv_dtype):
            sshape = (num_layers, num_blocks, block_size)
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
            if sharding is not None:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec
                if isinstance(sharding, NamedSharding):
                    rep = NamedSharding(sharding.mesh,
                                        PartitionSpec(None, None, None))
                    self.k_scale = jax.device_put(self.k_scale, rep)
                    self.v_scale = jax.device_put(self.v_scale, rep)
        # LIFO free list: freshly freed blocks are reused first (warm)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._tables = {}        # slot -> [physical block ids]
        self._lens = {}          # slot -> tokens stored
        self._refs = {}          # block id -> holders (never block 0)
        self.alloc_failures = 0  # pool-exhausted alloc attempts (stats)
        self.cow_copies = 0      # copy-on-write forks performed

    # -- refcount plumbing ----------------------------------------------

    def _pop_free(self):
        blk = self._free.pop()
        self._refs[blk] = 1
        return blk

    def ref(self, blk):
        """One more holder for an allocated block (prefix-cache chains,
        forked tables)."""
        if self._refs.get(blk, 0) < 1:
            raise DoubleFreeError(f"ref() on unallocated block {blk}")
        self._refs[blk] += 1

    def unref(self, blk):
        """Drop one holder; the block rejoins the free list at 0."""
        r = self._refs.get(blk, 0)
        if r < 1:
            raise DoubleFreeError(
                f"refcount underflow on block {blk} (double free)")
        if r == 1:
            del self._refs[blk]
            self._free.append(blk)
        else:
            self._refs[blk] = r - 1

    def refcount(self, blk):
        return self._refs.get(blk, 0)

    # -- allocation ------------------------------------------------------

    @property
    def num_free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        return (self.num_blocks - 1) - len(self._free)

    def utilization(self):
        """Fraction of allocatable blocks currently owned by sequences."""
        total = self.num_blocks - 1
        return self.blocks_in_use / total if total else 0.0

    @property
    def block_nbytes(self):
        """Exact bytes ONE block pins across both pools and all layers
        — INCLUDING the fp8 per-row scale arrays (ISSUE 20): a
        capacity claim that ignored its own scale overhead would lie
        — the flight recorder's memory block multiplies this by
        ``blocks_in_use`` (ISSUE 15 memory honesty)."""
        layers, _, bs, kvh, hd = self.k_pool.shape
        n = 2 * layers * bs * kvh * hd * self.k_pool.dtype.itemsize
        if self.k_scale is not None:
            n += 2 * layers * bs * self.k_scale.dtype.itemsize
        return n

    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` positions."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, slot, n_tokens):
        """Give ``slot`` enough blocks for ``n_tokens`` positions.
        Returns False (and allocates nothing) when the pool can't cover
        the request — the scheduler then leaves the request queued."""
        if slot in self._tables:
            raise MXNetError(f"slot {slot} already allocated; free() first")
        need = self.blocks_for(n_tokens)
        if need > len(self._free):
            self.alloc_failures += 1
            return False
        self._tables[slot] = [self._pop_free() for _ in range(need)]
        self._lens[slot] = 0
        return True

    def adopt(self, slot, blocks, n_tokens):
        """Create ``slot`` sharing ``blocks`` (a prefix-cache chain
        covering ``n_tokens`` positions): each block gains a holder, the
        slot's length starts at ``n_tokens``.  The slot grows past the
        shared prefix with ``ensure`` and CoW-forks on write."""
        if slot in self._tables:
            raise MXNetError(f"slot {slot} already allocated; free() first")
        if self.blocks_for(n_tokens) != len(blocks):
            raise MXNetError(
                f"adopt: {len(blocks)} blocks cannot cover {n_tokens} "
                f"tokens at block_size {self.block_size}")
        for blk in blocks:
            self.ref(blk)
        self._tables[slot] = list(blocks)
        self._lens[slot] = int(n_tokens)
        return True

    def ensure(self, slot, pos):
        """Grow ``slot``'s table to cover position ``pos`` (0-based).
        Returns False when the pool is exhausted (caller may evict or
        stall the sequence)."""
        table = self._tables[slot]
        need = self.blocks_for(pos + 1) - len(table)
        if need <= 0:
            return True
        if need > len(self._free):
            self.alloc_failures += 1
            return False
        table.extend(self._pop_free() for _ in range(need))
        return True

    def prepare_write(self, slot, start, end):
        """Copy-on-write plan for scattering K/V into positions
        ``[start, end)`` of ``slot``: every covering block with
        refcount > 1 is swapped for a fresh block in the table, and the
        (old, new) pairs are returned so the ENGINE can copy the block
        contents device-side before the write lands.  Returns None when
        the pool can't supply the fresh blocks (caller may evict and
        retry); [] when nothing is shared (the common path)."""
        if end <= start:
            return []
        table = self._tables[slot]
        copies = []
        first = int(start) // self.block_size
        last = (int(end) - 1) // self.block_size
        for idx in range(first, last + 1):
            old = table[idx]
            if self._refs.get(old, 0) > 1:
                if not self._free:
                    # undo the partial plan: nothing is copied until the
                    # whole range has fresh blocks
                    self.alloc_failures += 1
                    for o, n, i in copies:
                        del self._refs[n]
                        self._free.append(n)
                        table[i] = o
                        self._refs[o] = self._refs.get(o, 0) + 1
                        self.cow_copies -= 1
                    return None
                new = self._pop_free()
                table[idx] = new
                self.unref(old)
                copies.append((old, new, idx))
                self.cow_copies += 1
        return [(o, n) for o, n, _ in copies]

    def trim(self, slot, n_tokens):
        """Shrink ``slot``'s table to exactly cover ``n_tokens``
        positions, dropping this slot's hold on the tail blocks
        (prefill allocates for the padded BUCKET; the pad tail is
        garbage by construction — decode overwrites a position before
        ever reading it).  A tail block another holder still references
        survives in the pool; only refcount-0 blocks are recycled."""
        table = self._tables[slot]
        keep = self.blocks_for(n_tokens)
        while len(table) > keep:
            self.unref(table.pop())

    def free(self, slot):
        """Drop ``slot``'s hold on all of its blocks.  Freeing a slot
        that does not exist is a double free (typed error): the caller's
        lifecycle accounting is broken."""
        if slot not in self._tables:
            raise DoubleFreeError(f"free() on unknown slot {slot!r} "
                                  "(double free or never allocated)")
        for blk in self._tables.pop(slot):
            self.unref(blk)
        self._lens.pop(slot, None)

    def set_len(self, slot, n):
        self._lens[slot] = int(n)

    def seq_len(self, slot):
        return self._lens.get(slot, 0)

    def table(self, slot):
        return list(self._tables.get(slot, ()))

    def check_leaks(self, holders=0):
        """Invariant sweep for lifecycle tests: with all sequences
        released, every block must be back on the free list except the
        ``holders`` references held externally (e.g. a prefix cache's
        chains), and the refcount map must exactly cover the live
        tables + holders.  Raises MXNetError naming the discrepancy."""
        table_refs = {}
        for slot, table in self._tables.items():
            for blk in table:
                table_refs[blk] = table_refs.get(blk, 0) + 1
        extra = sum(self._refs.values()) - sum(table_refs.values())
        if extra != holders:
            raise MXNetError(
                f"KV block leak: {extra} dangling reference(s) beyond "
                f"the {holders} declared external holder(s); refs="
                f"{dict(self._refs)} tables={dict(self._tables)}")
        for blk, n in table_refs.items():
            if self._refs.get(blk, 0) < n:
                raise MXNetError(
                    f"block {blk} held by {n} table(s) but refcount is "
                    f"{self._refs.get(blk, 0)}")
        accounted = len(self._free) + len(self._refs)
        if accounted != self.num_blocks - 1:
            raise MXNetError(
                f"block accounting off: {len(self._free)} free + "
                f"{len(self._refs)} referenced != {self.num_blocks - 1} "
                "allocatable")
        return True

    # -- device-facing views --------------------------------------------

    def table_array(self, slots, width):
        """(len(slots), width) int32 block-table matrix for the compiled
        decode step: row i is ``slots[i]``'s table, padded with the null
        block; a ``None`` slot (inactive batch row) is all-null."""
        out = _np.zeros((len(slots), width), _np.int32)
        for i, slot in enumerate(slots):
            if slot is None:
                continue
            t = self._tables.get(slot, ())
            if len(t) > width:
                raise MXNetError(
                    f"slot {slot} holds {len(t)} blocks but the decode "
                    f"bucket only gathers {width}; bucket too small")
            out[i, :len(t)] = t[:width]
        return out

    def update_pools(self, k_pool, v_pool, k_scale=None, v_scale=None,
                     site="InferenceEngine.dispatch"):
        """Swap in the pools returned by a compiled (donated) step —
        and, under fp8 storage, the scale arrays that rode the same
        donated round-trip.  With the use-after-donate sentinel armed
        (MXTPU_DONATION_CHECK, ISSUE 16) the OLD arrays are poisoned at
        the swap: the donated executables consumed them, so any host
        touch of a stale reference after this point raises naming
        ``site``."""
        if _donation._ENABLED and self.k_pool is not k_pool:
            old = (self.k_pool, self.v_pool)
            if k_scale is not None and self.k_scale is not None:
                old += (self.k_scale, self.v_scale)
            _donation.poison(old, site=site)
        self.k_pool = k_pool
        self.v_pool = v_pool
        if k_scale is not None:
            self.k_scale = k_scale
            self.v_scale = v_scale

    def pool_args(self):
        """The device arrays a compiled graph takes (and returns,
        donated): ``(k_pool, v_pool)`` — plus the fp8 scale arrays
        when this cache stores scaled codes."""
        if self.k_scale is not None:
            return (self.k_pool, self.v_pool, self.k_scale, self.v_scale)
        return (self.k_pool, self.v_pool)

    def stats(self):
        shared = sum(1 for r in self._refs.values() if r > 1)
        return {"num_blocks": self.num_blocks,
                "kv_dtype": self.kv_dtype or "fp32",
                "block_size": self.block_size,
                "blocks_in_use": self.blocks_in_use,
                "utilization": round(self.utilization(), 4),
                "alloc_failures": self.alloc_failures,
                "sequences": len(self._tables),
                "shared_blocks": shared,
                "cow_copies": self.cow_copies}
