"""``mxnet_tpu.serving`` — compiled inference serving (ISSUE 7).

The serving vertical the ROADMAP's "millions of users" north star needs:

- :class:`InferenceEngine` — AOT-compiled prefill + single-token decode
  per power-of-two shape bucket over a paged KV cache; compile cache
  keyed and counted like the PR 1 retrace detector (zero compiles after
  warmup under traffic); optional int8 weight serving via
  ``contrib.quantization.quantize_net``.
- :class:`PagedKVCache` — block-table indexed K/V pool, per-sequence
  alloc/free, donated functional updates, per-block refcounts for
  copy-on-write prefix sharing (typed :class:`DoubleFreeError` on
  accounting violations).
- :class:`ContinuousBatcher` / :class:`StaticBatcher` — token-boundary
  continuous batching vs the fixed-batch baseline, over the same
  engine; with ``prefill_chunk`` set, admission packs chunks from
  several prompts into one dispatch (ISSUE 12).
- :mod:`frontend` — the multi-replica layer: :class:`PrefixCache`
  (system prompt prefilled once, blocks forked CoW per request) and
  :class:`Router` (least-loaded admission over N replicas, epoch-fenced
  membership, drain-and-requeue on death, one shared warmup compile
  cache).
- :class:`DraftSource` + the engine's ``verify`` graph family — ISSUE
  17 speculative decoding: model-free drafts (prefix-cache trie walk /
  prompt-lookup n-gram) scored K-at-a-time in one dispatch, greedy
  acceptance bitwise the plain decode stream; ``MXTPU_PAGED_ATTN``
  routes decode/verify attention through the Pallas paged kernel.

See docs/SERVING.md for the architecture and the bucket/compile-cache
math; ``tools/serve_loadgen.py`` is the load-generator benchmark.
"""
from __future__ import annotations

from .engine import InferenceEngine, next_bucket
from .kv_cache import PagedKVCache, DoubleFreeError, HandoffError
from .scheduler import ContinuousBatcher, Request, StaticBatcher
from .draft import DraftSource
from .frontend import PrefixCache, Router, AdmissionShed

__all__ = ["InferenceEngine", "PagedKVCache", "DoubleFreeError",
           "HandoffError", "ContinuousBatcher", "StaticBatcher",
           "Request", "next_bucket", "serving_block", "PrefixCache",
           "Router", "AdmissionShed", "DraftSource"]


def _r(x, nd=3):
    return None if x is None else round(float(x), nd)


def serving_block(max_batch=0, block_size=0, buckets=(), quantized=False,
                  continuous=True, requests=0, p50_ms=None, p99_ms=None,
                  ttft_p50_ms=None, tokens_s=None, tokens_s_chip=None,
                  occupancy=None, tokens_per_step=None,
                  compiles_after_warmup=None, cache_utilization=None,
                  chunked_prefill=False, router_replicas=0,
                  prefix_hit_rate=None, router_p99_ms=None,
                  speculative=False, paged_attn=False,
                  spec_accept_rate=None, tokens_per_dispatch=None,
                  tp_shards=0, disaggregated=False, handoff_ms=None,
                  prefill_pool_occupancy=None,
                  decode_pool_occupancy=None, kv_dtype="fp32",
                  kv_capacity_ratio=None, kv_decode_drift=None):
    """The bench.py ``serving`` observability block (the `comm` block
    discipline from PR 3/PR 5): static serving config is always real;
    MEASURED fields default to ``None`` — null-when-unmeasured, so a CPU
    run can never pass off an absent measurement as "latency is zero"
    (the PR 6 honesty rule, tests/test_bench_line.py).  ISSUE 12 grows
    the front-end fields: ``chunked_prefill``/``router_replicas`` are
    config (always real), ``prefix_hit_rate``/``router_p99_ms`` are
    measured (null until a run actually measured them).  ISSUE 17 adds
    ``speculative``/``paged_attn`` (config) and
    ``spec_accept_rate``/``tokens_per_dispatch`` (measured).  ISSUE 18
    adds ``tp_shards``/``disaggregated`` (config) and ``handoff_ms``/
    ``prefill_pool_occupancy``/``decode_pool_occupancy`` (measured —
    null unless a disaggregated run actually measured them).  ISSUE 20
    adds ``kv_dtype`` (config: the resolved KV storage mode) and
    ``kv_capacity_ratio``/``kv_decode_drift`` (measured — the blocks
    an equal byte budget holds vs f32, and the max |logit| drift of an
    fp8-KV decode vs the f32-KV engine)."""
    return {
        "max_batch": int(max_batch),
        "block_size": int(block_size),
        "buckets": list(int(b) for b in buckets),
        "quantized": bool(quantized),
        "continuous": bool(continuous),
        "requests": int(requests),
        "p50_ms": _r(p50_ms), "p99_ms": _r(p99_ms),
        "ttft_p50_ms": _r(ttft_p50_ms),
        "tokens_s": _r(tokens_s, 1), "tokens_s_chip": _r(tokens_s_chip, 1),
        "occupancy": _r(occupancy, 4),
        "tokens_per_step": _r(tokens_per_step, 3),
        "compiles_after_warmup": (None if compiles_after_warmup is None
                                  else int(compiles_after_warmup)),
        "cache_utilization": _r(cache_utilization, 4),
        "chunked_prefill": bool(chunked_prefill),
        "router_replicas": int(router_replicas),
        "prefix_hit_rate": _r(prefix_hit_rate, 4),
        "router_p99_ms": _r(router_p99_ms),
        "speculative": bool(speculative),
        "paged_attn": bool(paged_attn),
        "spec_accept_rate": _r(spec_accept_rate, 4),
        "tokens_per_dispatch": _r(tokens_per_dispatch, 3),
        "tp_shards": int(tp_shards),
        "disaggregated": bool(disaggregated),
        "handoff_ms": _r(handoff_ms),
        "prefill_pool_occupancy": _r(prefill_pool_occupancy, 4),
        "decode_pool_occupancy": _r(decode_pool_occupancy, 4),
        "kv_dtype": str(kv_dtype or "fp32"),
        "kv_capacity_ratio": _r(kv_capacity_ratio),
        "kv_decode_drift": (None if kv_decode_drift is None
                            else float(kv_decode_drift)),
    }
