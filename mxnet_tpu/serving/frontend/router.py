"""Multi-replica serving router: least-loaded admission, epoch-fenced
replica membership, drain-and-requeue on replica death.

One :class:`~mxnet_tpu.serving.InferenceEngine` is one chip's decode
loop; planet-scale traffic needs a FLEET of them behind one front end
(the GluonCV/GluonNLP deployment story, arXiv:1907.04433).  The Router
owns N engine replicas, each with its own
:class:`~mxnet_tpu.serving.ContinuousBatcher`, KV pool and prefix
cache, and:

- **admits** each request to the replica with the lowest load score,
  computed from the PR 9 telemetry registry signals
  (``serving.replica<i>.queue_depth`` / ``.ttft_ms`` /
  ``.kv_block_utilization`` — the same gauges a live scrape sees;
  direct engine reads are the fallback when the registry is off);
- **numbers the replica set with an epoch** (the PR 8 membership
  discipline): every death or join bumps it, and stats/manifest carry
  it so two observations of the fleet are comparable;
- **drains and requeues** when a replica dies mid-traffic: its queued,
  prefilling and mid-decode requests are reset to their prompts and
  re-admitted to the survivors — greedy decode is deterministic, so a
  re-run request produces the same tokens it would have (the chaos
  gate: zero lost, zero duplicated, outputs bitwise the solo run);
- **shares one warmup compile cache** across replicas: executables
  close over shapes only, so the fleet pays each (kind, size) graph
  compile once (replica 2's warmup skips straight through).

Two drive modes.  ``start()`` spawns one worker THREAD per replica
(each replica's engine/batcher/prefix cache is touched only by its
worker — single-owner, no data sharing; the router's own bookkeeping
is the only locked state).  ``drive()`` steps every live replica once
on the caller's thread, round-robin — fully deterministic, zero
sleeps, what the chaos scenario and the loadgen's reproducible numbers
use.  Both modes run the same admission/death/requeue code.
"""
from __future__ import annotations

import threading
from collections import deque

from ...base import MXNetError, NotSupportedError
from ... import telemetry as _telem
from ...telemetry import tracing as _trace
from ...lint import racecheck as _racecheck
from ..kv_cache import HandoffError
from ..scheduler import ContinuousBatcher

__all__ = ["Router", "Replica", "AdmissionShed"]


class AdmissionShed(MXNetError):
    """The router is shedding new admissions (degradation-ladder rung 1
    — capacity dropped below the healthy target).  In-flight and
    requeued requests are unaffected; the caller should back off or
    route elsewhere."""


class Replica:
    """One engine + batcher + (optional) worker thread.  Everything in
    here is owned by the replica's driver; the Router only reads/writes
    it while holding the router lock in ways the drivers tolerate
    (inbox hand-off, death flag)."""

    __slots__ = ("rid", "engine", "batcher", "alive", "inbox",
                 "boundaries", "thread", "ttfts", "role", "tpots")

    def __init__(self, rid, engine, batcher, role="combined"):
        self.rid = rid
        self.engine = engine
        self.batcher = batcher
        self.alive = True
        self.inbox = []          # guarded-by: Router._lock
        self.boundaries = 0      # scheduling boundaries stepped
        self.thread = None
        self.ttfts = []          # recent TTFTs (seconds) for scoring
        self.role = role         # combined | prefill | decode
        self.tpots = []          # recent TPOTs (seconds) for scaling

    def load_signals(self, inbox_len=0):
        """The raw admission signals, read directly off the replica —
        the fallback (and the source the Router publishes to the
        telemetry registry after every boundary).  ``inbox_len`` is
        snapshotted by the caller under the router lock (the inbox is
        the one cross-thread structure here)."""
        b = self.batcher
        depth = len(b.queue) + int(inbox_len) + len(b.active) + \
            len(getattr(b, "prefilling", ()))
        recent = self.ttfts[-8:]
        # None, not 0.0, before the first measured TTFT: an unmeasured
        # replica must read as "no signal", never as "perfect" (the
        # r04/r05 null-when-unmeasured convention — ISSUE 14 fix)
        ttft_ms = (sorted(recent)[len(recent) // 2] * 1e3
                   if recent else None)
        return {"queue_depth": depth,
                "ttft_ms": ttft_ms,
                "kv_block_utilization": self.engine.cache.utilization()}


class Router:
    """Front-end over ``replicas`` engine replicas.

    Parameters
    ----------
    engine_factory : callable(compile_cache_dict) -> InferenceEngine
        (unwarmed).  Called once per replica with the SHARED compile
        cache; the router warms each engine (replica 0 pays the
        compiles, the rest reuse them).  A DISAGGREGATED router calls
        it as ``engine_factory(cc, kv_cache=shared_or_None)`` — the
        first replica creates the pool, every later one must pass the
        given ``kv_cache`` through to its ``InferenceEngine``.
    replicas : fleet size (>= 1); default ``MXTPU_SERVE_REPLICAS`` or 2.
    prefills_per_step : forwarded to each ContinuousBatcher.
    now : timestamp source for router events (FakeClock-injectable;
        never used for waiting — the router has no timeouts).
    disaggregated : split the fleet into PREFILL-role and DECODE-role
        replicas over ONE shared ``PagedKVCache`` (ISSUE 18): a prefill
        replica fills a request's blocks, then hands ownership to a
        decode replica through the CoW refcounts (adopt-then-release);
        the autoscaler scales the pools independently (TTFT grows the
        prefill pool, TPOT the decode pool).  Default reads
        ``MXTPU_SERVE_DISAGG`` (unset/0 = off).  ``drive()`` only.
    """

    def __init__(self, engine_factory, replicas=None,
                 prefills_per_step=1, now=None, disaggregated=None):
        import os
        import time
        if replicas is None:
            try:
                replicas = int(os.environ.get("MXTPU_SERVE_REPLICAS", 2))
            except ValueError:
                replicas = 2
        if replicas < 1:
            raise MXNetError(f"Router needs >= 1 replica, got {replicas}")
        if disaggregated is None:
            disaggregated = os.environ.get(
                "MXTPU_SERVE_DISAGG", "") not in ("", "0")
        self.disaggregated = bool(disaggregated)
        if self.disaggregated and replicas < 2:
            raise MXNetError(
                "disaggregated serving needs >= 2 replicas (at least "
                f"one prefill and one decode), got {replicas}")
        self._now = now if now is not None else time.time
        self._lock = _racecheck.make_lock("Router._lock")
        self._cond = threading.Condition(self._lock)
        self.epoch = 0             # guarded-by: _lock (replica-set epoch)
        self.requeues = 0          # guarded-by: _lock
        self._assigned = {}        # guarded-by: _lock — req.id -> rid
        self._submitted = {}       # guarded-by: _lock — req.id -> Request
        self._stopping = False     # guarded-by: _lock
        self._shedding = False     # guarded-by: _lock (ladder rung 1)
        self.events = []           # guarded-by: _lock — membership log
        self._factory = engine_factory
        self._prefills_per_step = prefills_per_step
        self._notices = None       # elastic.NoticeBoard (ISSUE 13)
        self._trace_ctx = None     # ambient span captured at start()
        self.compile_cache = {}
        self.replicas = []
        self.handoffs = 0          # completed prefill->decode handoffs
        self._shared_cache = None  # disagg: the fleet-wide PagedKVCache
        warm0 = None
        for rid in range(replicas):
            role = self._role_for(rid)
            eng = self._make_engine()
            before = eng.stats["compiles"]
            eng.warmup()
            if rid == 0:
                warm0 = eng.stats["compiles"] - before
            self.replicas.append(Replica(rid, eng,
                                         self._make_batcher(eng, rid,
                                                            role),
                                         role=role))
        self.warmup_compiles = warm0 or 0
        self.warmup_compiles_shared = (replicas - 1) * (warm0 or 0)

    def _role_for(self, rid):
        """Disaggregated role placement: even rids prefill, odd rids
        decode — every fleet of >= 2 has at least one of each, and the
        autoscaler overrides per-pool via ``add_replica(role=...)``."""
        if not self.disaggregated:
            return "combined"
        return "prefill" if rid % 2 == 0 else "decode"

    def _make_engine(self):
        """Build one replica engine through the stored factory.  In
        disaggregated mode the factory is called with the fleet's
        SHARED ``kv_cache`` (None for the first replica, which creates
        the pool every later replica adopts) — block handoff is only
        meaningful when both sides index the same pool."""
        if not self.disaggregated:
            return self._factory(self.compile_cache)
        eng = self._factory(self.compile_cache,
                            kv_cache=self._shared_cache)
        if self._shared_cache is None:
            self._shared_cache = eng.cache
        elif eng.cache is not self._shared_cache:
            raise HandoffError(
                "disaggregated replicas must share one PagedKVCache — "
                "the engine_factory ignored its kv_cache argument")
        # the pool CREATOR's flag flips too: its pool outlives it (the
        # fleet shares it), so its death must free its slots like any
        # other disaggregated replica's
        eng.cache_shared = True
        return eng

    def _make_batcher(self, eng, rid, role):
        return ContinuousBatcher(
            eng, self._prefills_per_step,
            slot_ns=(rid if self.disaggregated else None), role=role)

    # -- membership ------------------------------------------------------

    def live_replicas(self):
        return [r for r in self.replicas if r.alive]

    def kill_replica(self, rid):
        """Administrative kill (chaos / tests): same path a crashed
        worker takes — epoch bump, drain, requeue."""
        self._on_death(self.replicas[rid],
                       MXNetError(f"replica {rid} killed"))

    def _evacuate(self, rep, event_kind, detail):
        """Take ``rep`` out of the replica set (epoch bump) and collect
        everything it still owed: inbox, queued, mid-prefill,
        mid-decode.  Finished requests already left the building.
        Returns the requests to requeue — shared by the crash path
        (``_on_death``) and the graceful drain (``drain_replica``)."""
        with self._lock:
            rep.alive = False
            self.epoch += 1
            epoch = self.epoch
            lost = list(rep.inbox)
            rep.inbox.clear()
            self.events.append(dict(detail, kind=event_kind,
                                    rid=rep.rid, epoch=epoch,
                                    t=self._now()))
            self._cond.notify_all()   # its worker thread must exit
        b = rep.batcher
        lost += list(b.queue)
        b.queue.clear()
        # slots the dead replica still holds: with a per-replica pool
        # they die with the engine, but a SHARED pool (disaggregated
        # fleet) outlives the replica — every hold must be dropped or
        # check_leaks on the survivors reports the dead replica's
        # blocks forever
        held_slots = (list(getattr(b, "prefilling", ()))
                      + list(b.active)
                      + [slot for slot, _req in
                         getattr(b, "handoff_ready", ())])
        lost += [st.req for st in getattr(b, "prefilling", {}).values()]
        getattr(b, "prefilling", {}).clear()
        lost += list(b.active.values())
        b.active.clear()
        lost += [req for _slot, req in getattr(b, "handoff_ready", ())]
        getattr(b, "handoff_ready", deque()).clear()
        if getattr(rep.engine, "cache_shared", False):
            for slot in held_slots:
                rep.engine.cache.free(slot)
            if rep.engine.prefix_cache is not None:
                rep.engine.prefix_cache.clear()
        return lost, epoch

    def _requeue_all(self, lost, from_rid=None):
        for req in lost:
            # reset to the prompt: greedy decode reproduces the exact
            # stream on the new replica, so nothing is lost or doubled
            req.generated = []
            req.finish_reason = None
            req.first_token_t = None
            req.finish_t = None
            req._queue_t0 = None
            if _trace.enabled() and req.trace is not None:
                # the requeue hop is an instant marker in the SAME
                # trace: the re-admission chain parents under the
                # original root, so a drained request's timeline stays
                # one causally-linked tree across replicas
                t = _trace.clock()
                _trace.record("requeue", t, t, parent=req.trace,
                              from_rid=from_rid)
            with self._lock:
                self.requeues += 1
            self.submit(req, _requeue=True)

    def _on_death(self, rep, exc):
        if not rep.alive:
            return
        lost, epoch = self._evacuate(
            rep, "replica_dead",
            {"error": f"{type(exc).__name__}: {exc}"})
        if not self.live_replicas():
            raise MXNetError(
                f"router: last replica died ({exc}); "
                f"{len(lost)} request(s) unservable")
        if self.disaggregated and not any(
                r.role == rep.role for r in self.live_replicas()):
            raise MXNetError(
                f"router: last {rep.role}-role replica died ({exc}); "
                f"the disaggregated fleet cannot serve without one")
        _telem.event("serving.replica_dead", rid=rep.rid,
                     epoch=epoch, requeued=len(lost))
        _telem.inc("serving.replica_deaths")
        self._requeue_all(lost, from_rid=rep.rid)

    def drain_replica(self, rid, reason="admin"):
        """Graceful exit for a DOOMED (preemption-noticed) or
        autoscaled-away replica: epoch bump, everything it still owed
        requeued to the survivors — zero lost, zero duplicated — and
        the replica leaves the set before its machine disappears.  Same
        evacuation as the crash path, minus the surprise."""
        rep = self.replicas[rid]
        if not rep.alive:
            return 0
        if len(self.live_replicas()) <= 1:
            raise MXNetError(
                f"router: refusing to drain replica {rid} — it is the "
                f"last live replica (scale up or stop shedding first)")
        if self.disaggregated and sum(
                1 for r in self.live_replicas()
                if r.role == rep.role) <= 1:
            raise MXNetError(
                f"router: refusing to drain replica {rid} — it is the "
                f"last live {rep.role}-role replica (grow that pool "
                "first)")
        lost, epoch = self._evacuate(rep, "replica_drained",
                                     {"reason": str(reason)})
        _telem.event("serving.replica_drained", rid=rep.rid,
                     epoch=epoch, requeued=len(lost),
                     reason=str(reason))
        _telem.inc("serving.replica_drains")
        self._requeue_all(lost, from_rid=rep.rid)
        return len(lost)

    def add_replica(self, role=None):
        """Grow the fleet by one replica (the autoscaler's grow path):
        built from the stored factory against the SHARED warmup compile
        cache (pool-geometry-keyed executables — the newcomer compiles
        nothing new for known shapes), epoch bump, worker thread
        spawned when the fleet runs threaded.  ``role`` targets a
        disaggregated pool ("prefill" | "decode"); default grows the
        smaller pool.  Non-disaggregated fleets reject explicit roles."""
        if not self.disaggregated:
            if role not in (None, "combined"):
                raise MXNetError(
                    f"add_replica(role={role!r}) needs a disaggregated "
                    "router (role'd replicas share one KV pool)")
            role = "combined"
        elif role is None:
            live = self.live_replicas()
            n_pre = sum(1 for r in live if r.role == "prefill")
            n_dec = sum(1 for r in live if r.role == "decode")
            role = "prefill" if n_pre <= n_dec else "decode"
        elif role not in ("prefill", "decode"):
            raise MXNetError(
                f"add_replica role {role!r} must be prefill|decode on "
                "a disaggregated router")
        eng = self._make_engine()
        eng.warmup()
        # self.replicas stays SINGLE-WRITER (the control loop that calls
        # add_replica) and append is atomic under the GIL; every
        # concurrent reader snapshots with list(self.replicas) — so the
        # list itself needs no lock, only the epoch/event bookkeeping
        rid = len(self.replicas)
        rep = Replica(rid, eng, self._make_batcher(eng, rid, role),
                      role=role)
        threaded = any(r.thread is not None for r in self.replicas)
        self.replicas.append(rep)
        with self._lock:
            self.epoch += 1
            epoch = self.epoch
            self.events.append({"kind": "replica_added", "rid": rid,
                                "epoch": epoch, "role": role,
                                "t": self._now()})
        _telem.event("serving.replica_added", rid=rid, epoch=epoch,
                     role=role)
        _telem.inc("serving.replica_adds")
        if threaded:
            t = threading.Thread(target=self._worker, args=(rep,),
                                 name=f"router-replica{rid}",
                                 daemon=True)
            rep.thread = t
            t.start()
        return rep

    # -- degradation / notices (ISSUE 13) --------------------------------

    def set_shedding(self, on, reason=None):
        """Degradation-ladder rung 1: while shedding, NEW submissions
        raise :class:`AdmissionShed`; requeues (drain/death evacuation)
        are exempt so nothing in flight is ever lost.  Idempotent; the
        transition (only) is logged."""
        on = bool(on)
        with self._lock:
            changed = on != self._shedding
            self._shedding = on
        if changed:
            _telem.event("serving.shedding", on=on,
                         reason=str(reason) if reason else None)
            _telem.set_gauge("serving.shedding", int(on))
        return on

    @property
    def shedding(self):
        with self._lock:
            return self._shedding

    def attach_notices(self, board):
        """Wire an :class:`~mxnet_tpu.elastic.NoticeBoard` whose ranks
        name replica ids: a pending notice drains the doomed replica at
        the next scheduling boundary (requeue to survivors, zero lost);
        a revoked notice cancels the drain before it commits."""
        self._notices = board
        return self

    def _check_notices(self):
        if self._notices is None:
            return 0
        self._notices.poll()
        drained = 0
        for notice in self._notices.pending():
            rid = notice.rank
            if rid >= len(self.replicas) or not self.replicas[rid].alive:
                self._notices.mark_drained(notice)   # already gone
                continue
            self._notices.mark_drained(notice)
            self.drain_replica(rid, reason=f"notice:{notice.kind}")
            drained += 1
        return drained

    # -- admission -------------------------------------------------------

    def _signals(self, rep):
        """Per-replica load signals THROUGH the telemetry registry when
        it's live (the published gauges are the fleet's source of
        truth), falling back to direct reads.  Unmeasured signals are
        ``None`` — "no signal", NEVER a fake-perfect 0.0 (the r04/r05
        null-when-unmeasured convention): scoring drops any signal not
        measured on every candidate rather than letting an unmeasured
        replica win admission on numbers nobody observed."""
        if _telem.enabled():
            pre = f"serving.replica{rep.rid}."
            depth = _telem.value(pre + "queue_depth")
            if depth is not None:
                return {"queue_depth": depth,
                        "ttft_ms": _telem.value(pre + "ttft_ms"),
                        "kv_block_utilization":
                            _telem.value(pre + "kv_block_utilization")}
        with self._lock:
            inbox_len = len(rep.inbox)
        return rep.load_signals(inbox_len)

    def _score(self, sig, use_ttft=True, use_kv=True):
        # queue depth dominates (each queued request is a whole
        # generation of latency); KV pressure breaks ties between
        # equally-deep queues; TTFT drift demotes a replica that has
        # been serving slowly even when its queue momentarily clears.
        # A signal class unmeasured on ANY candidate is excluded for
        # ALL (the caller passes use_*) — scores stay comparable and
        # admission falls back to queue depth alone when that is the
        # only signal every replica actually has.
        s = 2.0 * sig["queue_depth"]
        if use_kv:
            s += 1.0 * sig["kv_block_utilization"]
        if use_ttft:
            s += 0.001 * sig["ttft_ms"]
        return s

    def submit(self, request, _requeue=False):
        """Admit ``request`` to the least-loaded live replica.  While
        the degradation ladder has admissions SHED, new requests are
        rejected with a typed :class:`AdmissionShed` (requeues of
        in-flight work are exempt — a drain never loses a request)."""
        if not _requeue:
            with self._lock:
                shedding = self._shedding
            if shedding:
                _telem.inc("serving.admissions_shed")
                raise AdmissionShed(
                    "router is shedding new admissions (capacity below "
                    "the healthy target — degradation-ladder rung 1); "
                    "retry after capacity recovers")
        # decode-role replicas take work through block handoff, never
        # direct admission — a fresh prompt always needs a prefill
        live = [r for r in self.live_replicas() if r.role != "decode"]
        if not live:
            raise MXNetError("router: no live replicas that can admit")
        ta0 = _trace.clock() if _trace.enabled() else None
        sigs = [self._signals(r) for r in live]
        # null-honesty: only score on signal classes EVERY candidate
        # has measured; otherwise fall back to queue depth alone
        use_ttft = all(s["ttft_ms"] is not None for s in sigs)
        use_kv = all(s["kv_block_utilization"] is not None for s in sigs)
        scored = [(self._score(s, use_ttft, use_kv), r.rid, r)
                  for s, r in zip(sigs, live)]
        scored.sort(key=lambda t: (t[0], t[1]))
        rep = scored[0][2]
        if ta0 is not None:
            if request.trace is None:
                request.trace = _trace.start("request", id=request.id)
            _trace.record("admission", ta0, _trace.clock(),
                          parent=request.trace, rid=rep.rid,
                          requeue=bool(_requeue))
        with self._lock:
            if not _requeue:
                self._submitted[request.id] = request
            self._assigned[request.id] = rep.rid
            rep.inbox.append(request)
            self._cond.notify_all()
        return request

    def _drain_inbox(self, rep):
        with self._lock:
            pending, rep.inbox = rep.inbox, []
        for req in pending:
            rep.batcher.submit(req)

    # -- driving ---------------------------------------------------------

    def _step_replica(self, rep):
        """One scheduling boundary on one replica (runs on the
        replica's owner thread — worker or deterministic driver)."""
        from ...testing import faults
        rep.boundaries += 1
        faults.fault_point(f"serving.replica{rep.rid}.step",
                           payload=rep.boundaries)
        tb0 = _trace.clock() if _trace.enabled() else None
        self._drain_inbox(rep)
        n_fin = len(rep.batcher.finished)
        moved = rep.batcher.step()
        if rep.role == "prefill":
            moved += self._drain_handoffs(rep)
        for req in rep.batcher.finished[n_fin:]:
            t = req.ttft()
            if t is not None:
                rep.ttfts.append(t)
            tp = req.tpot()
            if tp is not None:
                rep.tpots.append(tp)
        if tb0 is not None:
            # boundary span parents under the driver's ambient trace
            # (the worker thread activates the context captured at
            # start(); drive() runs on the caller's own ambient)
            _trace.record("serving.boundary", tb0, _trace.clock(),
                          rid=rep.rid)
        if _telem.enabled():
            with self._lock:
                inbox_len = len(rep.inbox)
            sig = rep.load_signals(inbox_len)
            pre = f"serving.replica{rep.rid}."
            _telem.set_gauge(pre + "queue_depth", sig["queue_depth"])
            if sig["ttft_ms"] is not None:
                # never publish a fake-perfect 0.0 before the first
                # measured TTFT: the gauge stays absent => value() is
                # None => admission scoring treats it as "no signal"
                _telem.set_gauge(pre + "ttft_ms",
                                 round(sig["ttft_ms"], 3))
            _telem.set_gauge(pre + "kv_block_utilization",
                             round(sig["kv_block_utilization"], 4))
            recent = rep.tpots[-8:]
            if recent:
                # same null-honesty as ttft_ms: absent until measured
                _telem.set_gauge(
                    pre + "tpot_ms",
                    round(sorted(recent)[len(recent) // 2] * 1e3, 3))
        return moved

    def _pick_decode(self):
        """Least-loaded live decode-role replica with a free batch
        slot (None = the decode pool is saturated; the handoff entry
        waits in the prefill outbox — pure backpressure, no loss)."""
        cands = [r for r in self.live_replicas()
                 if r.role == "decode" and r.batcher._free_slots]
        if not cands:
            return None
        cands.sort(key=lambda r: (len(r.batcher.active), r.rid))
        return cands[0]

    def _drain_handoffs(self, rep):
        """Move ``rep``'s finished prefills to decode-role replicas:
        adopt-then-release over the SHARED pool's refcounts.  The fault
        point fires BEFORE any mutation, so a replica killed mid-
        handoff leaves the head entry wholly owned by the outbox — the
        evacuation path requeues it exactly once (the chaos gate: zero
        lost, zero duplicated).  Entries the decode pool cannot take
        yet stay parked (retried next boundary)."""
        from ...testing import faults
        b = rep.batcher
        moved = 0
        while b.handoff_ready:
            slot, req = b.handoff_ready[0]
            tgt = self._pick_decode()
            if tgt is None:
                break
            faults.fault_point(f"serving.replica{rep.rid}.handoff",
                               payload=req.id)
            t0 = _telem.clock() if _telem.enabled() else None
            cache = rep.engine.cache
            n = cache.seq_len(slot)
            cache.trim(slot, n)   # drop bucket-padding past the prompt
            dst = tgt.batcher.adopt_handoff(req, cache.table(slot), n)
            if dst is None:
                break
            b.complete_handoff(slot)
            b.handoff_ready.popleft()
            with self._lock:
                self._assigned[req.id] = tgt.rid
                self.handoffs += 1
            moved += 1
            if t0 is not None:
                _telem.inc("serving.handoffs")
                _telem.observe("serving.handoff_ms",
                               (_telem.clock() - t0) * 1e3)
            if _trace.enabled():
                t = _trace.clock()
                _trace.record("handoff", t, t, parent=req.trace,
                              from_rid=rep.rid, to_rid=tgt.rid,
                              blocks=len(cache.table(dst)))
        return moved

    def _replica_idle(self, rep):
        b = rep.batcher
        return not (rep.inbox or b.queue or b.active
                    or getattr(b, "prefilling", None)
                    or getattr(b, "handoff_ready", None))

    def drive(self, max_boundaries=100000):
        """Deterministic mode: round-robin every live replica until all
        submitted requests finish.  Zero sleeps, zero threads — the
        chaos scenario's and the loadgen's reproducible path."""
        boundaries = 0
        while not self.all_done():
            self._check_notices()   # drain doomed replicas first
            progressed = False
            for rep in list(self.replicas):
                if not rep.alive or self._replica_idle(rep):
                    continue
                try:
                    self._step_replica(rep)
                except Exception as e:  # noqa: BLE001 — death path
                    self._on_death(rep, e)
                progressed = True
                boundaries += 1
                if boundaries > max_boundaries:
                    raise MXNetError("router drive exceeded "
                                     "max_boundaries — fleet wedged")
            if not progressed and not self.all_done():
                raise MXNetError(
                    "router: no replica can make progress but "
                    "requests remain (pool too small for the mix?)")
        return boundaries

    # -- threaded mode ---------------------------------------------------

    def start(self):
        """Spawn one worker thread per replica (production shape).
        Each worker owns its replica exclusively; it sleeps on the
        router condition variable when idle (no polling)."""
        if self.disaggregated:
            raise NotSupportedError(
                "threaded disaggregated serving is not supported yet: "
                "the block handoff crosses two replicas' batchers, "
                "which breaks the one-owner-thread-per-replica "
                "discipline — use drive()")
        self._trace_ctx = _trace.capture()
        for rep in self.replicas:
            if rep.thread is not None:
                continue
            t = threading.Thread(target=self._worker, args=(rep,),
                                 name=f"router-replica{rep.rid}",
                                 daemon=True)
            rep.thread = t
            t.start()
        return self

    def _worker(self, rep):
        # worker spans parent under the trace ambient at start()
        # (ISSUE 14 cross-thread propagation)
        with _trace.activate(getattr(self, "_trace_ctx", None)):
            self._worker_loop(rep)

    def _worker_loop(self, rep):
        while True:
            with self._lock:
                while (rep.alive and not self._stopping
                       and self._replica_idle(rep)):
                    self._cond.wait()  # mxlint: disable=HB16 -- Condition.wait RELEASES the router lock while sleeping
                if self._stopping or not rep.alive:
                    return
            board = self._notices
            if board is not None:
                # single-owner discipline: the DOOMED replica's own
                # worker performs its drain (it owns the batcher state)
                board.poll()
                notice = board.pending_for(rep.rid)
                if notice is not None:
                    board.mark_drained(notice)
                    self.drain_replica(rep.rid,
                                       reason=f"notice:{notice.kind}")
                    return
            try:
                self._step_replica(rep)
            except Exception as e:  # noqa: BLE001 — death path
                self._on_death(rep, e)
                return
            finally:
                with self._lock:
                    self._cond.notify_all()

    def stop(self):
        """Stop workers after they finish the current boundary."""
        with self._lock:
            self._stopping = True
            self._cond.notify_all()
        for rep in self.replicas:
            if rep.thread is not None:
                rep.thread.join(timeout=60)
                rep.thread = None
        with self._lock:
            self._stopping = False
        return self

    def wait_all_done(self, timeout=60.0):
        """Threaded mode: block until every submitted request finished.
        Event-driven, not polled — workers notify the router condition
        after every boundary."""
        import time
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                reqs = list(self._submitted.values())
                if all(r.done for r in reqs):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise MXNetError(
                        "router: requests still unfinished at timeout")
                self._cond.wait(remaining)  # mxlint: disable=HB16 -- Condition.wait RELEASES the router lock while sleeping

    # -- introspection ---------------------------------------------------

    def all_done(self):
        with self._lock:
            reqs = list(self._submitted.values())
        return all(r.done for r in reqs)

    def finished(self):
        """Every finished request across live AND dead replicas (a
        request that completed before its replica died stays
        completed)."""
        out = []
        for rep in self.replicas:
            out.extend(rep.batcher.finished)
        return out

    def manifest(self):
        """The fleet's inspectable shape: epoch, per-replica liveness +
        engine config + mesh spec (the ISSUE 12 small-fix: the recorded
        MeshConfig rides along so item-2 TP serving slots in here)."""
        with self._lock:
            epoch = self.epoch
        return {
            "epoch": epoch,
            "disaggregated": self.disaggregated,
            "replicas": [{
                "rid": r.rid,
                "alive": r.alive,
                "role": r.role,
                "cache_shared": getattr(r.engine, "cache_shared",
                                        False),
                "mesh": r.engine.mesh_config.describe(),
                "max_batch": r.engine.max_batch,
                "block_size": r.engine.block_size,
                "max_context": r.engine.max_context,
                "buckets": list(r.engine.buckets),
                "quantized": r.engine.quantized,
                "prefill_chunk": r.engine.prefill_chunk,
                "prefix_cache": r.engine.prefix_cache is not None,
            } for r in self.replicas],
            "shared_compile_cache": len(self.compile_cache),
            "warmup_compiles": self.warmup_compiles,
            "warmup_compiles_shared": self.warmup_compiles_shared,
        }

    def stats(self):
        fin = self.finished()
        lat = sorted(r.latency() for r in fin
                     if r.latency() is not None)

        def pct(p):
            if not lat:
                return None
            return lat[min(len(lat) - 1, int(round(p * (len(lat) - 1))))]

        per_replica = []
        total_caw = 0
        pool_occ = {"prefill": [], "decode": []}
        for r in self.replicas:
            occ = r.batcher.occupancy()
            total_caw += r.engine.stats["compiles_after_warmup"]
            if r.role in pool_occ and occ is not None:
                pool_occ[r.role].append(occ)
            per_replica.append({
                "rid": r.rid, "alive": r.alive, "role": r.role,
                "requests": len(r.batcher.finished),
                "boundaries": r.boundaries,
                "occupancy": round(occ, 4) if occ is not None else None,
                "prefix": (r.engine.prefix_cache.stats()
                           if r.engine.prefix_cache else None),
            })
        with self._lock:
            epoch, requeues = self.epoch, self.requeues
            shedding = self._shedding
            handoffs = self.handoffs

        def _pool(vals):
            # None, not 0.0, until a pool member measured something
            return round(sum(vals) / len(vals), 4) if vals else None

        return {"replicas": len(self.replicas),
                "live": len(self.live_replicas()),
                "epoch": epoch,
                "disaggregated": self.disaggregated,
                "requests": len(fin),
                "requeues": requeues,
                "handoffs": handoffs,
                "prefill_pool_occupancy": _pool(pool_occ["prefill"]),
                "decode_pool_occupancy": _pool(pool_occ["decode"]),
                "shedding": shedding,
                "p50_latency_s": pct(0.50), "p99_latency_s": pct(0.99),
                "compiles_after_warmup": total_caw,
                "per_replica": per_replica}
