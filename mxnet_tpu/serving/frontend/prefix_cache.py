"""Copy-on-write prefix cache: hash token prefixes to KV block chains.

The serving north star is millions of requests that all start with the
same system prompt.  PR 7's engine recomputes that prompt per request;
this cache remembers, per PHYSICAL BLOCK, which token chain produced
it, so a request whose prompt starts with a cached chain adopts the
blocks (refcount bump, zero compute) and only the un-cached suffix is
prefilled (through the engine's packed chunk graph).

Structure: a trie of nodes keyed by ``(parent_key, block_tokens)`` —
the dict key IS the hash of the whole token prefix up to that block
(each key embeds its parent's key, the rolling-hash construction at
block granularity).  Full-block nodes chain; one PARTIAL tail node per
insertion remembers a block whose last positions are still unwritten
(a 12-token system prompt at block_size 8 caches one full block plus a
4-token partial).  Adopting a partial block is exactly where
copy-on-write earns its keep: the adopter's next write lands in that
block, ``PagedKVCache.prepare_write`` sees refcount > 1 and forks it,
and the cached original keeps serving other requests bit-identically.

Every node holds ONE reference on its block.  Eviction (LRU, leaf
first) only drops that reference — a block a live sequence still reads
has refcount > 1 and stays in the pool untouched, so eviction under
block pressure can never corrupt an in-flight request (the ISSUE 12
acceptance gate).

Single-owner discipline: a PrefixCache belongs to ONE engine replica
and is only touched from that replica's driver (thread or the Router's
deterministic drive) — no lock, by design; the Router never shares one
across replicas.
"""
from __future__ import annotations

from ...base import MXNetError
from ... import telemetry as _telem

__all__ = ["PrefixCache"]


class _Node:
    __slots__ = ("key", "parent", "block", "n_tokens", "partial",
                 "children", "tick")

    def __init__(self, key, parent, block, n_tokens, partial, tick):
        self.key = key
        self.parent = parent          # parent _Node or None (root child)
        self.block = block            # physical block id (one ref held)
        self.n_tokens = n_tokens      # tokens cached in this block
        self.partial = partial        # True: block tail still unwritten
        self.children = 0             # live child-node count
        self.tick = tick              # LRU stamp (deterministic counter)


class PrefixCache:
    """Block-chain prefix cache over one :class:`PagedKVCache`.

    Parameters
    ----------
    cache : the engine's PagedKVCache (chains hold refs on its blocks).
    max_nodes : soft cap on cached nodes; inserting past it evicts LRU
        leaves first (0 = unbounded, eviction only under pool pressure).
    """

    def __init__(self, cache, max_nodes=0):
        self.cache = cache
        self.max_nodes = int(max_nodes)
        self._nodes = {}      # key -> _Node
        # parent_key -> set of child keys: the downward index the
        # draft-source trie walk needs (lookup/attach only ever descend
        # by KNOWN tokens; a draft asks "what comes next?")
        self._childmap = {}
        self._tick = 0        # deterministic LRU clock (no wall time)
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0   # positions served from cache, cumulative
        self.evictions = 0

    # -- key construction ------------------------------------------------

    @staticmethod
    def _key(parent_key, tokens):
        return (parent_key, tuple(int(t) for t in tokens))

    def _bump(self, node):
        self._tick += 1
        # refresh the whole chain: a leaf hit keeps its ancestors warm
        # (an ancestor must never be evicted before its children anyway,
        # but LRU order should reflect reachability)
        while node is not None:
            node.tick = self._tick
            node = node.parent

    # -- the read path ---------------------------------------------------

    def lookup(self, tokens):
        """Longest cached chain prefixing ``tokens``, capped at
        ``len(tokens) - 1`` positions (at least one token must be
        computed to produce logits).  Returns ``(n_tokens, blocks)``
        with refcounts UNTOUCHED — :meth:`attach` takes the references.
        """
        bs = self.cache.block_size
        toks = [int(t) for t in tokens]
        limit = len(toks) - 1
        parent = None
        parent_key = None
        blocks = []
        n = 0
        while n + bs <= limit:
            key = self._key(parent_key, toks[n:n + bs])
            node = self._nodes.get(key)
            if node is None or node.partial:
                break
            blocks.append(node.block)
            n += bs
            parent, parent_key = node, key
        # partial tail: the longest cached sub-block continuation
        for ln in range(min(bs - 1, limit - n), 0, -1):
            key = self._key(parent_key, toks[n:n + ln])
            node = self._nodes.get(key)
            if node is not None and node.partial:
                blocks.append(node.block)
                n += ln
                parent = node
                break
        self.lookups += 1
        if n:
            self.hits += 1
            self.hit_tokens += n
            self._bump(parent)
        self._publish()
        return n, blocks

    def continuation(self, tokens, k):
        """Draft-source trie walk (ISSUE 17): the cached continuation of
        the FULL sequence ``tokens``, up to ``k`` tokens — what some
        earlier request generated/prompted AFTER this exact prefix.

        Refcount-NEUTRAL by contract: a draft is a guess for the verify
        step, not an adoption — no references are taken, no LRU ticks
        are spent, no hit accounting moves.  The returned tokens stay
        valid even if the chain is evicted before the verify dispatch
        (they are plain ints; a wrong guess just fails acceptance)."""
        bs = self.cache.block_size
        toks = [int(t) for t in tokens]
        n = 0
        parent_key = None
        # descend the full-block chain covering ``tokens`` exactly
        while n + bs <= len(toks):
            key = self._key(parent_key, toks[n:n + bs])
            node = self._nodes.get(key)
            if node is None or node.partial:
                break
            n += bs
            parent_key = key
        rem = tuple(toks[n:])
        out = []
        while len(out) < int(k):
            nxt = None
            # deterministic: smallest token tuple among matching children
            for key in sorted(self._childmap.get(parent_key, ()),
                              key=lambda kk: kk[1]):
                bt = key[1]
                if len(bt) > len(rem) and bt[:len(rem)] == rem:
                    node = self._nodes.get(key)
                    if node is not None:
                        nxt = (key, node, bt)
                        break
            if nxt is None:
                break
            key, node, bt = nxt
            out.extend(bt[len(rem):])
            if node.partial:
                break             # partial tail: the chain ends here
            parent_key, rem = key, ()
        return out[:int(k)]

    def attach(self, slot, tokens):
        """Adopt the longest cached chain into ``slot`` (one ref per
        block) and return the cached position count (0 = miss; the
        caller allocates from scratch)."""
        n, blocks = self.lookup(tokens)
        if n:
            self.cache.adopt(slot, blocks, n)
        return n

    # -- the write path --------------------------------------------------

    def insert(self, slot, tokens):
        """Register ``slot``'s prefilled prompt: one node per full
        block, plus a partial node for the tail sub-block (if any and
        if at least one token long).  Blocks already chained are
        skipped; new nodes take one reference each so the chain
        survives the sequence's release."""
        bs = self.cache.block_size
        toks = [int(t) for t in tokens]
        table = self.cache.table(slot)
        parent = None
        parent_key = None
        n = 0
        idx = 0
        while n + bs <= len(toks):
            key = self._key(parent_key, toks[n:n + bs])
            node = self._nodes.get(key)
            if node is None:
                node = self._new_node(key, parent, table[idx],
                                      bs, partial=False)
                if node is None:    # cap reached, nothing evictable
                    return
            parent, parent_key = node, key
            n += bs
            idx += 1
        rem = len(toks) - n
        if rem > 0 and idx < len(table):
            key = self._key(parent_key, toks[n:])
            if key not in self._nodes:
                self._new_node(key, parent, table[idx], rem, partial=True)

    def _new_node(self, key, parent, block, n_tokens, partial):
        if self.max_nodes and len(self._nodes) >= self.max_nodes:
            if not self.evict(blocks_needed=0, nodes_needed=1):
                return None
        self.cache.ref(block)
        self._tick += 1
        node = _Node(key, parent, block, n_tokens, partial, self._tick)
        self._nodes[key] = node
        self._childmap.setdefault(key[0], set()).add(key)
        if parent is not None:
            parent.children += 1
        return node

    # -- eviction --------------------------------------------------------

    def evict(self, blocks_needed=1, nodes_needed=0):
        """Drop LRU LEAF nodes until the pool has ``blocks_needed``
        free blocks (and/or ``nodes_needed`` node slots).  Only the
        cache's own reference is dropped — a block a live sequence
        shares keeps its other refcounts and is NOT returned to the
        free list (``PagedKVCache.unref`` recycles at zero only).
        Returns the number of nodes evicted."""
        dropped = 0
        while (self.cache.num_free_blocks < blocks_needed or
               dropped < nodes_needed):
            leaves = [nd for nd in self._nodes.values()
                      if nd.children == 0]
            if not leaves:
                break
            victim = min(leaves, key=lambda nd: nd.tick)
            del self._nodes[victim.key]
            sibs = self._childmap.get(victim.key[0])
            if sibs is not None:
                sibs.discard(victim.key)
                if not sibs:
                    del self._childmap[victim.key[0]]
            if victim.parent is not None:
                victim.parent.children -= 1
            self.cache.unref(victim.block)
            self.evictions += 1
            dropped += 1
        self._publish()
        return dropped

    def clear(self):
        """Drop every chain (shutdown / tests)."""
        for node in self._nodes.values():
            self.cache.unref(node.block)
        self._nodes.clear()
        self._childmap.clear()

    # -- stats -----------------------------------------------------------

    def hit_rate(self):
        return self.hits / self.lookups if self.lookups else None

    def held_blocks(self):
        """References this cache holds (the ``holders`` argument for
        ``PagedKVCache.check_leaks``)."""
        return len(self._nodes)

    def _publish(self):
        if _telem.enabled():
            hr = self.hit_rate()
            if hr is not None:
                _telem.set_gauge("serving.prefix_hit_rate",
                                 round(hr, 4))

    def stats(self):
        return {"nodes": len(self._nodes),
                "lookups": self.lookups, "hits": self.hits,
                "hit_rate": (round(self.hit_rate(), 4)
                             if self.lookups else None),
                "hit_tokens": self.hit_tokens,
                "evictions": self.evictions}
