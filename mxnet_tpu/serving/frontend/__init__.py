"""``mxnet_tpu.serving.frontend`` — the multi-replica serving front end
(ISSUE 12).

Three pieces turn PR 7's single-replica engine into a servable fleet:

- :class:`PrefixCache` — hashes token prefixes to KV block chains so a
  system prompt shared by every request is prefilled ONCE; per-request
  blocks fork copy-on-write (``PagedKVCache`` refcounts), and LRU
  eviction only ever reclaims chains no live request still reads.
- chunked/batched prefill — the engine's ``chunk`` graph family plus
  ``ContinuousBatcher``'s packed admission: several queued prompts (and
  the tail chunks of long ones) ride ONE prefill dispatch per boundary.
- :class:`Router` — N engine replicas behind least-loaded admission on
  the PR 9 registry signals, an epoch-numbered replica set, death ->
  drain -> requeue with zero lost or duplicated requests, and one
  shared warmup compile cache for the whole fleet.

See docs/SERVING.md §Front-end; the chaos gate is
``tools/tpu_queue_runner.py --chaos serving``.
"""
from __future__ import annotations

from .prefix_cache import PrefixCache
from .router import Router, Replica, AdmissionShed

__all__ = ["PrefixCache", "Router", "Replica", "AdmissionShed"]
