"""AOT-compiled bucketed inference engine for Llama-family decoders.

Serving can't afford a retrace mid-traffic (PR 1's retrace detector
exists because one recompile stalls every request on the chip), so the
engine AOT-compiles TWO graph families at warmup and only ever looks
them up afterwards:

- ``prefill[bucket]``: a full causal forward over a prompt padded to a
  power-of-two sequence bucket, writing K/V (unrepeated GQA heads) into
  the sequence's pool blocks and sampling the first generated token from
  the last valid position's logits.
- ``decode[n_blocks]``: ONE token for the whole fixed-size batch against
  the paged KV cache — block-table gather, per-row position mask, the
  shared ``llama._cache_attention`` math (bitwise the full forward, see
  the decode-parity gate in tests/test_serving.py), current K/V
  scattered into the pool before attending, next token sampled in-graph.

Both families take the KV pools as DONATED arguments (the PR 6
``step_multi`` carry discipline): the cache is updated functionally and
swapped on the host, never copied.  Weights are jit arguments, never
baked constants.  The compile cache is keyed like PR 1's retrace
detector — every (kind, shape-signature) miss is counted, and
``stats["compiles_after_warmup"]`` staying 0 under traffic is a tier-1
assertion.

int8 serving: pass ``quantize="int8"`` (+ calibration batches) and the
engine routes the net through ``contrib.quantization.quantize_net`` —
the projection weights become per-channel int8 with the calibrated
activation scales, and the engine's matmuls mirror ``QuantizedDense``
op-for-op (int32 accumulation is exact, so decode parity survives
quantization bit-for-bit against the quantized net's own forward).
"""
from __future__ import annotations

import math
import os

import numpy as _np

from ..base import MXNetError
from .. import telemetry as _telem
from .kv_cache import PagedKVCache

__all__ = ["InferenceEngine", "next_bucket"]


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def next_bucket(n, buckets):
    """Smallest bucket >= n, or None when n exceeds every bucket."""
    for b in buckets:
        if n <= b:
            return b
    return None


class InferenceEngine:
    """Compiled serving engine over one ``LlamaForCausalLM``.

    Parameters
    ----------
    net : initialized LlamaForCausalLM (run one forward first so shapes
        are materialized).  With ``quantize="int8"`` the net's Dense
        projections are swapped for int8 twins IN PLACE via
        ``contrib.quantization.quantize_net``.
    max_batch : decode slots (>= 2; the compiled decode batch is fixed).
    block_size : KV-cache block size in tokens (power of two).
    max_context : longest supported sequence (rounded down to a multiple
        of ``block_size``); prefill/decode buckets are the powers of two
        in [block_size, max_context].
    temperature / top_k / seed : in-graph sampling config (greedy at
        temperature 0; otherwise top-k categorical when top_k > 0, full
        categorical when 0).
    """

    def __init__(self, net, max_batch=None, block_size=None,
                 num_blocks=None, max_context=None, temperature=0.0,
                 top_k=0, seed=0, quantize=None, calib_data=None,
                 num_calib_batches=10):
        import jax
        import jax.numpy as jnp
        cfg = net.cfg
        if cfg.tensor_parallel:
            raise MXNetError("InferenceEngine drives the single-chip "
                             "decode path; TP models serve via forward()")
        if quantize not in (None, "int8"):
            raise MXNetError(f"quantize={quantize!r}: only int8 weight "
                             "quantization is supported")
        self.net = net
        self.cfg = cfg
        self.max_batch = max(2, _env_int("MXTPU_SERVE_MAX_BATCH", 4)
                             if max_batch is None else int(max_batch))
        bs = _env_int("MXTPU_SERVE_BLOCK", 16) if block_size is None \
            else int(block_size)
        mc = max_context if max_context is not None else \
            min(cfg.max_seq_len, _env_int("MXTPU_SERVE_MAX_CONTEXT", 1024))
        mc = (mc // bs) * bs
        if mc < bs:
            raise MXNetError(f"max_context {mc} < block_size {bs}")
        self.block_size = bs
        self.max_context = mc
        # shape buckets: powers of two in [block_size, max_context] —
        # each bucket is one compiled graph, so traffic of ANY length
        # mix runs on this fixed, warmup-compiled set
        self.buckets = []
        b = bs
        while b <= mc:
            self.buckets.append(b)
            b *= 2
        if num_blocks is None:
            num_blocks = 1 + self.max_batch * (mc // bs)
        self.quantized = False
        if quantize == "int8":
            self._quantize_in_place(net, calib_data, num_calib_batches)
        self.params = self._extract_weights(net)
        self.cache = PagedKVCache(
            cfg.num_layers, cfg.num_kv_heads, cfg.head_dim,
            num_blocks=num_blocks, block_size=bs,
            max_batch=self.max_batch,
            dtype=self.params["embed"].dtype)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self._base_key = jax.random.key(seed)
        self._compiled = {}
        self._warmed = False
        self.stats = {"compiles": 0, "compiles_after_warmup": 0,
                      "prefill_calls": 0, "decode_calls": 0}

    # -- weights ---------------------------------------------------------

    def _quantize_in_place(self, net, calib_data, num_calib_batches):
        from ..contrib.quantization import QuantizedDense, quantize_net
        has_q = any(isinstance(m, QuantizedDense) for m in
                    self._walk(net))
        if not has_q:
            if calib_data is None:
                raise MXNetError("quantize='int8' needs calib_data "
                                 "(token batches for PTQ calibration)")
            # calibration hooks pull activations host-side, which is
            # illegal inside a jitted forward — run the calibration
            # forwards eagerly, then restore hybridization
            was_active = getattr(net, "_active", False)
            if was_active:
                net.hybridize(False)
            try:
                quantize_net(net, calib_data=calib_data,
                             num_calib_batches=num_calib_batches)
            finally:
                if was_active:
                    net.hybridize(True)
        self.quantized = True

    @staticmethod
    def _walk(block):
        yield block
        for child in block._children.values():
            yield from InferenceEngine._walk(child)

    def _proj_params(self, layer):
        """One projection as a tagged dict: {'w'} fp32 or
        {'qw','ws','as'} int8 (QuantizedDense twins)."""
        import jax.numpy as jnp
        from ..contrib.quantization import QuantizedDense
        if isinstance(layer, QuantizedDense):
            return {"qw": layer.quantized_weight,
                    "ws": layer.weight_scale.astype(jnp.float32),
                    "as": jnp.float32(layer.act_scale)}
        return {"w": layer.weight.data().data}

    def _extract_weights(self, net):
        m = net.model
        layers = []
        for layer in m.layers:
            a, f = layer.attention, layer.mlp
            layers.append({
                "in_norm": layer.input_norm.weight.data().data,
                "q": self._proj_params(a.q_proj),
                "k": self._proj_params(a.k_proj),
                "v": self._proj_params(a.v_proj),
                "o": self._proj_params(a.o_proj),
                "post_norm": layer.post_norm.weight.data().data,
                "gate": self._proj_params(f.gate_proj),
                "up": self._proj_params(f.up_proj),
                "down": self._proj_params(f.down_proj),
            })
        params = {"embed": m.embed.weight.data().data,
                  "norm": m.norm.weight.data().data,
                  "layers": layers}
        if net.lm_head is not None:
            params["head"] = self._proj_params(net.lm_head)
        return params

    # -- graph building --------------------------------------------------

    @staticmethod
    def _proj(x, p):
        """Dense matmul mirroring the block forwards op-for-op:
        fp32 = FullyConnected's ``x @ w.T``; int8 = QuantizedDense's
        round/clip -> int8 dot_general(int32 accum) -> rescale."""
        import jax.numpy as jnp
        from jax import lax
        if "qw" in p:
            lead = x.shape[:-1]
            flat = x.reshape(-1, x.shape[-1])
            qx = jnp.clip(jnp.round(flat / p["as"]), -127, 127) \
                .astype(jnp.int8)
            acc = lax.dot_general(qx, p["qw"], (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (p["as"] *
                                             p["ws"].reshape(1, -1))
            return out.reshape(lead + (out.shape[-1],))
        return jnp.matmul(x, p["w"].T)

    def _head_logits(self, params, x):
        import jax.numpy as jnp
        if "head" in params:
            return self._proj(x, params["head"])
        return jnp.matmul(x, params["embed"].T)

    def _build_prefill(self, bucket):
        """Prefill graph for one prompt padded to ``bucket`` tokens:
        causal forward (the same flash path the full forward runs),
        K/V written into the sequence's blocks, first token sampled from
        the last VALID position's logits."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ..gluon.model_zoo.nlp.llama import (_QPAD, _rms,
                                                 _rot_interleaved)
        from ..ops.flash_attention import flash_attention
        cfg = self.cfg
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        rep, eps, theta = h // kvh, cfg.rms_eps, cfg.rope_theta
        bs = self.block_size
        nb = bucket // bs
        L = bucket

        def run(params, kp, vp, toks, valid, bt, key):
            x = jnp.take(params["embed"], toks, axis=0)      # (1, L, hid)
            pos = jnp.arange(L)
            freqs = theta ** (-jnp.arange(0, d, 2) / d)
            ang = pos[:, None] * freqs[None, :]
            cos, sin = jnp.cos(ang), jnp.sin(ang)
            for li, lp in enumerate(params["layers"]):
                hh = _rms(x, lp["in_norm"], eps)
                q = self._proj(hh, lp["q"]).reshape(1, L, h, d) \
                    .transpose(0, 2, 1, 3)
                k = self._proj(hh, lp["k"]).reshape(1, L, kvh, d) \
                    .transpose(0, 2, 1, 3)
                v = self._proj(hh, lp["v"]).reshape(1, L, kvh, d) \
                    .transpose(0, 2, 1, 3)
                q = _rot_interleaved(q, cos, sin)
                k = _rot_interleaved(k, cos, sin)
                # unrepeated K/V into the pool blocks: (L, kvh, d) rows
                kp = kp.at[li, bt].set(
                    k[0].transpose(1, 0, 2).reshape(nb, bs, kvh, d))
                vp = vp.at[li, bt].set(
                    v[0].transpose(1, 0, 2).reshape(nb, bs, kvh, d))
                kr = jnp.repeat(k, rep, axis=1)
                vr = jnp.repeat(v, rep, axis=1)
                o = flash_attention(q, kr, vr, causal=True)
                o = o.transpose(0, 2, 1, 3).reshape(1, L, h * d)
                x = x + self._proj(o, lp["o"])
                y = _rms(x, lp["post_norm"], eps)
                x = x + self._proj(
                    jax.nn.silu(self._proj(y, lp["gate"])) *
                    self._proj(y, lp["up"]), lp["down"])
            x = _rms(x, params["norm"], eps)
            # last-valid-row logits through an M=_QPAD slice (an M=1
            # projection takes XLA's gemv path whose bits differ from
            # the full forward's gemm — see llama._QPAD)
            start = jnp.maximum(valid - _QPAD, 0)
            xs = lax.dynamic_slice_in_dim(x, start, _QPAD, axis=1)
            logits = self._head_logits(params, xs)[0]        # (_QPAD, V)
            last = jnp.take(logits, valid - 1 - start, axis=0)
            tok = self._sample(last[None, :], key)[0]
            return last, tok, kp, vp

        return run

    def _build_decode(self, nbl):
        """One-token decode for the fixed batch against ``nbl`` gathered
        blocks per sequence (context bucket = nbl * block_size)."""
        import jax
        import jax.numpy as jnp
        from ..gluon.model_zoo.nlp.llama import (_cache_attention, _rms,
                                                 _rot_interleaved)
        cfg = self.cfg
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        eps, theta = cfg.rms_eps, cfg.rope_theta
        bs = self.block_size
        B = self.max_batch
        L = nbl * bs
        scale = 1.0 / math.sqrt(d)

        def run(params, kp, vp, toks, pos, bts, active, key):
            x = jnp.take(params["embed"], toks, axis=0)      # (B, hid)
            freqs = theta ** (-jnp.arange(0, d, 2) / d)
            ang = pos[:, None] * freqs[None, :]              # (B, d/2)
            cos, sin = jnp.cos(ang), jnp.sin(ang)
            blk = jnp.take_along_axis(
                bts, (pos // bs)[:, None], axis=1)[:, 0]     # (B,)
            blk = jnp.where(active, blk, 0)                  # null block
            off = pos % bs
            valid = jnp.arange(L)[None, :] <= pos[:, None]   # (B, L)
            for li, lp in enumerate(params["layers"]):
                hh = _rms(x, lp["in_norm"], eps)
                q = self._proj(hh, lp["q"]).reshape(B, h, d)
                k = self._proj(hh, lp["k"]).reshape(B, kvh, d)
                v = self._proj(hh, lp["v"]).reshape(B, kvh, d)
                q = _rot_interleaved(q, cos[:, None, :], sin[:, None, :])
                k = _rot_interleaved(k, cos[:, None, :], sin[:, None, :])
                kp = kp.at[li, blk, off].set(k)
                vp = vp.at[li, blk, off].set(v)
                ck = kp[li][bts].reshape(B, L, kvh, d) \
                    .transpose(0, 2, 1, 3)                   # (B,kvh,L,d)
                cv = vp[li][bts].reshape(B, L, kvh, d) \
                    .transpose(0, 2, 1, 3)
                o = _cache_attention(q, ck, cv, valid, scale)
                x = x + self._proj(o, lp["o"])
                y = _rms(x, lp["post_norm"], eps)
                x = x + self._proj(
                    jax.nn.silu(self._proj(y, lp["gate"])) *
                    self._proj(y, lp["up"]), lp["down"])
            logits = self._head_logits(params, _rms(x, params["norm"],
                                                    eps))    # (B, V)
            return logits, self._sample(logits, key), kp, vp

        return run

    def _sample(self, logits, key):
        """In-graph next-token sampling: greedy at temperature 0, else
        (top-k) categorical — logits never leave the device per token."""
        import jax
        import jax.numpy as jnp
        if self.temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / self.temperature
        if self.top_k > 0:
            vals, idx = jax.lax.top_k(scaled, self.top_k)
            pick = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(
                idx, pick[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, scaled,
                                      axis=-1).astype(jnp.int32)

    # -- compile cache (the retrace-detector discipline) -----------------

    def _get(self, kind, size, args):
        """Compile-cache lookup keyed by (kind, shape-signature); every
        miss is one AOT compile (``jit(...).lower(args).compile()``) and
        is COUNTED — serving traffic after warmup() must never miss.
        The cached object is a fixed executable, so an unexpected
        shape/dtype drift raises loudly instead of retracing silently
        (the PR 1 retrace-detector discipline, enforced not observed)."""
        sig = (kind, size)
        fn = self._compiled.get(sig)
        if fn is None:
            import jax
            build = (self._build_prefill if kind == "prefill"
                     else self._build_decode)(size)
            fn = jax.jit(build, donate_argnums=(1, 2)) \
                .lower(*args).compile()
            self._compiled[sig] = fn
            self.stats["compiles"] += 1
            _telem.inc("serving.compiles")
            if self._warmed:
                # the tier-1 zero-retrace assertion reads the engine's
                # own counter; the registry twin is what a live scrape
                # sees (one source of truth for bench/loadgen, ISSUE 9)
                self.stats["compiles_after_warmup"] += 1
                _telem.inc("serving.compiles_after_warmup")
                _telem.event("serving.compile_after_warmup",
                             kind=kind, size=int(size))
        return fn

    def warmup(self):
        """AOT-compile every (prefill, decode) bucket graph by running
        each once against the real pools (compile + execute warms the
        jit cache; the pools round-trip through the donated call)."""
        import jax
        dummy_key = jax.random.key(0)
        for bucket in self.buckets:
            nb = bucket // self.block_size
            ok = self.cache.alloc("__warmup__", bucket)
            if not ok:
                raise MXNetError("warmup: KV pool too small for bucket "
                                 f"{bucket}; raise num_blocks")
            bt = _np.asarray(self.cache.table("__warmup__"), _np.int32)
            toks = _np.zeros((1, bucket), _np.int32)
            args = (self.params, self.cache.k_pool, self.cache.v_pool,
                    toks, _np.int32(1), bt, dummy_key)
            last, tok, kp, vp = self._get("prefill", bucket, args)(*args)
            self.cache.update_pools(kp, vp)
            bts = self.cache.table_array(
                ["__warmup__"] + [None] * (self.max_batch - 1), nb)
            args = (self.params, self.cache.k_pool, self.cache.v_pool,
                    _np.zeros((self.max_batch,), _np.int32),
                    _np.zeros((self.max_batch,), _np.int32), bts,
                    _np.zeros((self.max_batch,), bool), dummy_key)
            logits, nxt, kp, vp = self._get("decode", nb, args)(*args)
            self.cache.update_pools(kp, vp)
            self.cache.free("__warmup__")
        self._warmed = True
        return self

    # -- serving calls ---------------------------------------------------

    def prefill(self, slot, tokens):
        """Prefill ``tokens`` (1D int sequence) into ``slot``: allocates
        blocks, runs the bucketed prefill graph, samples the first
        generated token.  Returns ``(first_token, last_logits)`` or None
        when the prompt exceeds max_context or the pool is exhausted
        (request stays queued)."""
        import jax
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        t = toks.shape[0]
        if t == 0:
            raise MXNetError("prefill needs at least one token")
        bucket = next_bucket(t, self.buckets)
        if bucket is None:
            return None
        if not self.cache.alloc(slot, bucket):
            return None
        padded = _np.zeros((1, bucket), _np.int32)
        padded[0, :t] = toks
        bt = _np.asarray(self.cache.table(slot), _np.int32)
        key = jax.random.fold_in(self._base_key,
                                 (1 << 30) + self.stats["prefill_calls"])
        args = (self.params, self.cache.k_pool, self.cache.v_pool,
                padded, _np.int32(t), bt, key)
        t0 = _telem.clock() if _telem.enabled() else None
        last, tok, kp, vp = self._get("prefill", bucket, args)(*args)
        self.cache.update_pools(kp, vp)
        self.cache.trim(slot, t)
        self.cache.set_len(slot, t)
        self.stats["prefill_calls"] += 1
        if t0 is not None:
            _telem.inc("serving.prefill_calls")
            _telem.observe("serving.prefill_ms",
                           (_telem.clock() - t0) * 1e3)
            _telem.set_gauge("serving.kv_block_utilization",
                             round(self.cache.utilization(), 4))
        return int(tok), last

    def reserve(self, slot, pos):
        """Grow ``slot``'s block table to cover ``pos`` before a decode
        step; False when the pool is exhausted."""
        return self.cache.ensure(slot, pos)

    def decode(self, entries):
        """One decode step for the joined batch.

        entries: list of (slot, token, position) for the ACTIVE rows
        (position = where this token goes, i.e. current sequence
        length).  Pads to the fixed batch, picks the context bucket from
        the max position, gathers block tables, runs the compiled step.
        Returns (next_tokens (n_active,) np.int32, logits rows).
        """
        import jax
        if not entries:
            raise MXNetError("decode: empty batch")
        n = len(entries)
        if n > self.max_batch:
            raise MXNetError(f"decode batch {n} > max_batch")
        max_pos = max(p for _, _, p in entries)
        bucket = next_bucket(max_pos + 1, self.buckets)
        if bucket is None:
            raise MXNetError(f"position {max_pos} exceeds max_context "
                             f"{self.max_context}")
        nbl = bucket // self.block_size
        slots = [s for s, _, _ in entries] + \
            [None] * (self.max_batch - n)
        toks = _np.zeros((self.max_batch,), _np.int32)
        pos = _np.zeros((self.max_batch,), _np.int32)
        active = _np.zeros((self.max_batch,), bool)
        for i, (slot, tok, p) in enumerate(entries):
            toks[i], pos[i], active[i] = tok, p, True
            self.cache.set_len(slot, p + 1)
        bts = self.cache.table_array(slots, nbl)
        key = jax.random.fold_in(self._base_key,
                                 self.stats["decode_calls"])
        args = (self.params, self.cache.k_pool, self.cache.v_pool,
                toks, pos, bts, active, key)
        t0 = _telem.clock() if _telem.enabled() else None
        logits, nxt, kp, vp = self._get("decode", nbl, args)(*args)
        self.cache.update_pools(kp, vp)
        self.stats["decode_calls"] += 1
        if t0 is not None:
            _telem.inc("serving.decode_calls")
            _telem.observe("serving.decode_ms",
                           (_telem.clock() - t0) * 1e3)
            _telem.set_gauge("serving.kv_block_utilization",
                             round(self.cache.utilization(), 4))
        nxt = _np.asarray(nxt)[:n]
        return nxt, _np.asarray(logits)[:n]

    def release(self, slot):
        """Finished sequence: return its blocks to the pool."""
        self.cache.free(slot)
