"""AOT-compiled bucketed inference engine for Llama-family decoders.

Serving can't afford a retrace mid-traffic (PR 1's retrace detector
exists because one recompile stalls every request on the chip), so the
engine AOT-compiles TWO graph families at warmup and only ever looks
them up afterwards:

- ``prefill[bucket]``: a full causal forward over a prompt padded to a
  power-of-two sequence bucket, writing K/V (unrepeated GQA heads) into
  the sequence's pool blocks and sampling the first generated token from
  the last valid position's logits.
- ``decode[n_blocks]``: ONE token for the whole fixed-size batch against
  the paged KV cache — block-table gather, per-row position mask, the
  shared ``llama._cache_attention`` math (bitwise the full forward, see
  the decode-parity gate in tests/test_serving.py), current K/V
  scattered into the pool before attending, next token sampled in-graph.

Both families take the KV pools as DONATED arguments (the PR 6
``step_multi`` carry discipline): the cache is updated functionally and
swapped on the host, never copied.  Weights are jit arguments, never
baked constants.  The compile cache is keyed like PR 1's retrace
detector — every (kind, shape-signature) miss is counted, and
``stats["compiles_after_warmup"]`` staying 0 under traffic is a tier-1
assertion.

int8 serving: pass ``quantize="int8"`` (+ calibration batches) and the
engine routes the net through ``contrib.quantization.quantize_net`` —
the projection weights become per-channel int8 with the calibrated
activation scales, and the engine's matmuls mirror ``QuantizedDense``
op-for-op (int32 accumulation is exact, so decode parity survives
quantization bit-for-bit against the quantized net's own forward).

ISSUE 12 adds a third graph family for the serving FRONT-END
(``mxnet_tpu.serving.frontend``):

- ``chunk[n_blocks]``: a PACKED continuation prefill — up to
  ``max_batch`` rows, each a chunk of up to ``MXTPU_PREFILL_CHUNK``
  prompt tokens starting at an arbitrary position, attending to that
  row's already-cached K/V through its block table (offset-causal
  mask).  One dispatch admits several queued prompts of a boundary
  (chunked/batched prefill) AND computes only the un-cached suffix of
  a prompt whose prefix the :class:`~.frontend.PrefixCache` already
  holds.  The chunk math mirrors the cold prefill's flash path
  op-for-op (same blockwise online-softmax, same mask constant), so
  the K/V it writes — and therefore every later decode logit — is
  BITWISE the cold path's (tests/test_serving_frontend.py).
- ``cow``: a one-block pool copy, the device half of the kv-cache's
  copy-on-write fork (a shared block is copied before its first
  write; every other holder keeps the original bits).

Both are compiled at warmup like the rest; ``compiles_after_warmup``
still gates zero retraces.  Replicas behind one
:class:`~.frontend.Router` pass a shared ``compile_cache`` so the
fleet pays each graph compile once.

ISSUE 17 adds the SPECULATIVE graph family:

- ``verify[(k, n_blocks)]``: ``k`` (power-of-two bucket) decode steps
  UNROLLED inside one dispatch — step ``w`` feeds the row's ``w``-th
  token (the last committed token, then the draft continuation) at
  position ``pos + w``, writes its K/V through the block table, and
  argmaxes the next token; the functional kp/vp threading makes step
  ``w``'s writes visible to step ``w+1``.  Each unrolled step is the
  ``decode`` body op-for-op (same projections, same
  ``_cache_attention``/paged-attention routing, same scatter), so the
  greedy token at every ACCEPTED position is bitwise the plain decode
  path's — the acceptance gate is exact token equality, never a
  tolerance (the PR 7 decode-parity contract extended through the
  multi-step seam, device-resident like PR 6's ``step_multi``).
  Rows with fewer real tokens than the bucket mask their dead steps
  into the null block; a row with ONE token is exactly a plain decode
  row, which is how mixed draft/no-draft batches share the dispatch.
  Speculation is greedy-only (temperature 0) — acceptance compares
  argmaxes, so sampled decoding keeps the plain path.

``MXTPU_PAGED_ATTN=1`` reroutes the decode/verify cache attention
through ``ops.paged_attention.paged_decode_attention`` — whose XLA
fallback is the inline gather + ``_cache_attention`` verbatim (bitwise
on CPU; the Pallas gather-by-block-table kernel engages on TPU hosts).
"""
from __future__ import annotations

import math
import os

import numpy as _np

from ..base import MXNetError, NotSupportedError
from .. import telemetry as _telem
from ..telemetry import tracing as _trace
from .kv_cache import PagedKVCache

__all__ = ["InferenceEngine", "next_bucket"]


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def next_bucket(n, buckets):
    """Smallest bucket >= n, or None when n exceeds every bucket."""
    for b in buckets:
        if n <= b:
            return b
    return None


class InferenceEngine:
    """Compiled serving engine over one ``LlamaForCausalLM``.

    Parameters
    ----------
    net : initialized LlamaForCausalLM (run one forward first so shapes
        are materialized).  With ``quantize="int8"`` the net's Dense
        projections are swapped for int8 twins IN PLACE via
        ``contrib.quantization.quantize_net``.
    max_batch : decode slots (>= 2; the compiled decode batch is fixed).
    block_size : KV-cache block size in tokens (power of two).
    max_context : longest supported sequence (rounded down to a multiple
        of ``block_size``); prefill/decode buckets are the powers of two
        in [block_size, max_context].
    temperature / top_k / seed : in-graph sampling config (greedy at
        temperature 0; otherwise top-k categorical when top_k > 0, full
        categorical when 0).
    mesh : a ``parallel.MeshConfig`` (or spec string).  tp > 1 serves
        the model SHARDED over a tp submesh (ISSUE 18): extracted
        weights are placed at rest with the
        ``tensor_parallel.llama_engine_specs`` megatron table, the
        paged KV pools are sharded on the kv-head axis, and every
        graph family compiles against the sharded layouts (the mesh
        spec rides in the compile-cache signature).  pp > 1 still
        raises the typed ``NotSupportedError``.  None reads
        ``MXTPU_SERVE_TP`` (default unset = the single-chip engine,
        bitwise-inert).
    kv_cache : an existing ``PagedKVCache`` to ADOPT instead of
        building one (ISSUE 18 disaggregated serving: prefill and
        decode replicas share one physical pool so a block handoff
        transfers ownership, not bytes).  Geometry must match this
        engine's net and ``block_size``.
    prefill_chunk : chunk bucket in tokens (multiple of block_size) for
        the packed continuation-prefill family; 0/None reads
        ``MXTPU_PREFILL_CHUNK`` (default off).
    prefix_cache : True builds a ``frontend.PrefixCache`` over this
        engine's KV pool; None reads ``MXTPU_PREFIX_CACHE``.
    compile_cache : dict shared across replicas of a ``frontend.Router``
        so the fleet pays each graph compile once (signatures carry the
        pool geometry, so mismatched engines never collide).
    spec_decode : True compiles the speculative ``verify`` graph family
        at warmup (greedy-only — requires temperature 0); None reads
        ``MXTPU_SPEC_DECODE`` (default off: no extra warmup compiles,
        bitwise the PR 7 engine).
    spec_k : max draft tokens scored per verify dispatch (>= 1); the
        compiled widths are the power-of-two buckets covering
        ``spec_k + 1`` fed tokens.  None reads ``MXTPU_SPEC_K``
        (default 4).
    paged_attn : True routes decode/verify cache attention through
        ``ops.paged_attention`` (Pallas gather-by-block-table on TPU;
        bitwise-identical XLA fallback elsewhere); None reads
        ``MXTPU_PAGED_ATTN`` (default off = the inline gather).
    kv_dtype : KV-cache STORAGE precision (ISSUE 20): ``"fp8"`` stores
        e4m3 codes with per-token-row amax scales (quantize-on-write /
        dequantize-in-attention threaded through every graph family —
        the attention math itself stays f32, so drift is bounded by
        the storage rounding alone); ``"bf16"`` stores bfloat16;
        ``"fp32"``/None-resolved-empty is today's engine, bitwise.
        None reads ``MXTPU_KV_DTYPE`` (default unset).  Prefill's OWN
        attention reads the fresh f32 K/V, so the first generated
        token never drifts; decode/verify/chunk read the pool.
    """

    def __init__(self, net, max_batch=None, block_size=None,
                 num_blocks=None, max_context=None, temperature=0.0,
                 top_k=0, seed=0, quantize=None, calib_data=None,
                 num_calib_batches=10, mesh=None, prefill_chunk=None,
                 prefix_cache=None, compile_cache=None,
                 spec_decode=None, spec_k=None, paged_attn=None,
                 kv_cache=None, kv_dtype=None):
        import jax
        import jax.numpy as jnp
        from ..ops import quant_kv as _qkv
        from ..parallel.mesh import MeshConfig
        cfg = net.cfg
        if cfg.tensor_parallel:
            raise NotSupportedError(
                "InferenceEngine extracts and places its own weights "
                "(pass mesh=MeshConfig(tp=N) for sharded serving); "
                "structurally tensor_parallel nets serve via forward()")
        if quantize not in (None, "int8"):
            raise MXNetError(f"quantize={quantize!r}: only int8 weight "
                             "quantization is supported")
        if mesh is None:
            tp_env = _env_int("MXTPU_SERVE_TP", 0)
            if tp_env > 1:
                mesh = MeshConfig(tp=tp_env)
        if isinstance(mesh, str):
            mesh = MeshConfig.from_spec(mesh)
        self.mesh_config = mesh if mesh is not None else MeshConfig()
        if self.mesh_config.pp > 1:
            raise NotSupportedError(
                f"mesh {self.mesh_config.describe()!r}: serving over "
                "the pp axis is still unsupported (tp submeshes serve "
                "since ISSUE 18; pipeline-staged serving is a later "
                "follow-up)")
        self.tp = self.mesh_config.tp
        self._mesh = None
        if self.tp > 1:
            if quantize is not None:
                raise NotSupportedError(
                    "int8 serving on a tp submesh is not supported; "
                    "serve quantized nets on single-chip replicas")
            need = self.mesh_config.dp * self.tp * self.mesh_config.pp
            ndev = len(jax.devices())
            if need > ndev:
                raise MXNetError(
                    f"mesh {self.mesh_config.describe()!r} needs "
                    f"{need} devices; only {ndev} visible")
            if cfg.num_heads % self.tp or cfg.num_kv_heads % self.tp:
                raise MXNetError(
                    f"tp={self.tp} must divide num_heads "
                    f"{cfg.num_heads} and num_kv_heads "
                    f"{cfg.num_kv_heads}")
            if cfg.intermediate_size % self.tp:
                raise MXNetError(
                    f"tp={self.tp} must divide intermediate_size "
                    f"{cfg.intermediate_size}")
            self._mesh = self.mesh_config.build()
        self.net = net
        self.cfg = cfg
        self.max_batch = max(2, _env_int("MXTPU_SERVE_MAX_BATCH", 4)
                             if max_batch is None else int(max_batch))
        bs = _env_int("MXTPU_SERVE_BLOCK", 16) if block_size is None \
            else int(block_size)
        mc = max_context if max_context is not None else \
            min(cfg.max_seq_len, _env_int("MXTPU_SERVE_MAX_CONTEXT", 1024))
        mc = (mc // bs) * bs
        if mc < bs:
            raise MXNetError(f"max_context {mc} < block_size {bs}")
        self.block_size = bs
        self.max_context = mc
        # shape buckets: powers of two in [block_size, max_context] —
        # each bucket is one compiled graph, so traffic of ANY length
        # mix runs on this fixed, warmup-compiled set
        self.buckets = []
        b = bs
        while b <= mc:
            self.buckets.append(b)
            b *= 2
        if num_blocks is None:
            num_blocks = 1 + self.max_batch * (mc // bs)
        self.quantized = False
        if quantize == "int8":
            self._quantize_in_place(net, calib_data, num_calib_batches)
        # KV storage precision (ISSUE 20): resolved ONCE here; every
        # graph builder branches on it at trace time, so an unset knob
        # compiles exactly today's graphs (the bitwise kill switch)
        self.kv_dtype = _qkv.resolve_kv_dtype(kv_dtype)
        self._kv_fp8 = _qkv.kv_has_scales(self.kv_dtype)
        self.params = self._extract_weights(net)
        if self._mesh is not None:
            self.params = self._shard_params(self.params)
        if kv_cache is not None:
            # disaggregated serving (ISSUE 18): prefill and decode
            # replicas ADOPT one physical pool — the block handoff is
            # an ownership transfer through the CoW refcounts, never a
            # copy.  Geometry must match or the compiled graphs would
            # gather garbage.
            if (kv_cache.num_layers != cfg.num_layers
                    or kv_cache.num_kv_heads != cfg.num_kv_heads
                    or kv_cache.head_dim != cfg.head_dim
                    or kv_cache.block_size != bs
                    or kv_cache.kv_dtype != self.kv_dtype
                    or (self.kv_dtype is None and
                        kv_cache.dtype != self.params["embed"].dtype)):
                raise MXNetError(
                    "kv_cache geometry mismatch: shared pool is "
                    f"(layers={kv_cache.num_layers}, "
                    f"kvh={kv_cache.num_kv_heads}, "
                    f"hd={kv_cache.head_dim}, "
                    f"bs={kv_cache.block_size}, "
                    f"kv_dtype={kv_cache.kv_dtype or 'fp32'}) vs this "
                    f"engine's (layers={cfg.num_layers}, "
                    f"kvh={cfg.num_kv_heads}, hd={cfg.head_dim}, "
                    f"bs={bs}, kv_dtype={self.kv_dtype or 'fp32'})")
            self.cache = kv_cache
            self.cache_shared = True
        else:
            pool_sharding = None
            if self._mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                from ..parallel.mesh import AXIS_TP
                pool_sharding = NamedSharding(
                    self._mesh,
                    PartitionSpec(None, None, None, AXIS_TP, None))
            self.cache = PagedKVCache(
                cfg.num_layers, cfg.num_kv_heads, cfg.head_dim,
                num_blocks=num_blocks, block_size=bs,
                max_batch=self.max_batch,
                dtype=self.params["embed"].dtype,
                sharding=pool_sharding,
                kv_dtype=self.kv_dtype or "fp32")
            self.cache_shared = False
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self._base_key = jax.random.key(seed)
        # compile cache: pass one dict to every replica of a Router and
        # the whole fleet pays each (kind, size) compile exactly once —
        # executables close over shapes only (weights/pools are jit
        # ARGUMENTS), so replicas with identical config share freely
        self._compiled = {} if compile_cache is None else compile_cache
        self._warmed = False
        # chunked/batched prefill (ISSUE 12): chunk bucket in tokens;
        # 0 disables the family (no extra warmup compiles)
        pc = _env_int("MXTPU_PREFILL_CHUNK", 0) if prefill_chunk is None \
            else int(prefill_chunk)
        if pc < 0 or (pc and pc % bs):
            raise MXNetError(f"prefill_chunk {pc} must be a positive "
                             f"multiple of block_size {bs} (or 0=off)")
        self.prefill_chunk = min(pc, mc)
        # copy-on-write prefix cache: True builds one, an instance is
        # adopted, None reads MXTPU_PREFIX_CACHE (default off so the
        # cold engine's block accounting is exactly PR 7's)
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "MXTPU_PREFIX_CACHE", "0") not in ("", "0")
        if prefix_cache is True:
            from .frontend.prefix_cache import PrefixCache
            self.prefix_cache = PrefixCache(self.cache)
        else:
            self.prefix_cache = prefix_cache or None
        # speculative decoding (ISSUE 17): kill switch default-off so
        # the cold engine compiles nothing extra and is bitwise PR 7's
        if spec_decode is None:
            spec_decode = os.environ.get(
                "MXTPU_SPEC_DECODE", "0") not in ("", "0")
        self.spec_decode = bool(spec_decode)
        self.spec_k = _env_int("MXTPU_SPEC_K", 4) if spec_k is None \
            else int(spec_k)
        if self.spec_k < 1:
            raise MXNetError(f"spec_k {self.spec_k} must be >= 1")
        if self.spec_decode and self.temperature != 0.0:
            raise NotSupportedError(
                "speculative decoding is greedy-only (acceptance "
                "compares argmaxes bitwise); serve temperature > 0 "
                "with MXTPU_SPEC_DECODE=0")
        # paged decode-attention kernel routing (ISSUE 17): default off
        # keeps the inline gather; on CPU the op's fallback is that
        # gather verbatim, so the knob is bitwise-inert off-TPU
        if paged_attn is None:
            paged_attn = os.environ.get(
                "MXTPU_PAGED_ATTN", "0") not in ("", "0")
        self.paged_attn = bool(paged_attn)
        self.stats = {"compiles": 0, "compiles_after_warmup": 0,
                      "prefill_calls": 0, "decode_calls": 0,
                      "chunk_prefill_calls": 0,
                      "prompt_tokens_computed": 0,
                      "verify_calls": 0, "draft_tokens_scored": 0}

    # -- weights ---------------------------------------------------------

    def _quantize_in_place(self, net, calib_data, num_calib_batches):
        from ..contrib.quantization import QuantizedDense, quantize_net
        has_q = any(isinstance(m, QuantizedDense) for m in
                    self._walk(net))
        if not has_q:
            if calib_data is None:
                raise MXNetError("quantize='int8' needs calib_data "
                                 "(token batches for PTQ calibration)")
            # calibration hooks pull activations host-side, which is
            # illegal inside a jitted forward — run the calibration
            # forwards eagerly, then restore hybridization
            was_active = getattr(net, "_active", False)
            if was_active:
                net.hybridize(False)
            try:
                quantize_net(net, calib_data=calib_data,
                             num_calib_batches=num_calib_batches)
            finally:
                if was_active:
                    net.hybridize(True)
        self.quantized = True

    @staticmethod
    def _walk(block):
        yield block
        for child in block._children.values():
            yield from InferenceEngine._walk(child)

    def _proj_params(self, layer):
        """One projection as a tagged dict: {'w'} fp32 or
        {'qw','ws','as'} int8 (QuantizedDense twins)."""
        import jax.numpy as jnp
        from ..contrib.quantization import QuantizedDense
        if isinstance(layer, QuantizedDense):
            return {"qw": layer.quantized_weight,
                    "ws": layer.weight_scale.astype(jnp.float32),
                    "as": jnp.float32(layer.act_scale)}
        return {"w": layer.weight.data().data}

    def _extract_weights(self, net):
        m = net.model
        layers = []
        for layer in m.layers:
            a, f = layer.attention, layer.mlp
            layers.append({
                "in_norm": layer.input_norm.weight.data().data,
                "q": self._proj_params(a.q_proj),
                "k": self._proj_params(a.k_proj),
                "v": self._proj_params(a.v_proj),
                "o": self._proj_params(a.o_proj),
                "post_norm": layer.post_norm.weight.data().data,
                "gate": self._proj_params(f.gate_proj),
                "up": self._proj_params(f.up_proj),
                "down": self._proj_params(f.down_proj),
            })
        params = {"embed": m.embed.weight.data().data,
                  "norm": m.norm.weight.data().data,
                  "layers": layers}
        if net.lm_head is not None:
            params["head"] = self._proj_params(net.lm_head)
        return params

    # -- tp sharding (ISSUE 18) ------------------------------------------

    def _shard_params(self, params):
        """Place the extracted weights on the tp submesh AT REST:
        column-parallel projections (q/k/v/gate/up) shard their output
        features, row-parallel ones (o/down) their input features —
        the ``tensor_parallel.llama_engine_specs`` megatron table —
        and embeddings/norms/head replicate.  Placement happens once
        here; the AOT-lowered executables bake these input shardings
        in, so a drifted layout fails loudly instead of resharding
        silently per dispatch."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.tensor_parallel import llama_engine_specs
        mesh = self._mesh
        specs = llama_engine_specs()

        def put(w, spec):
            return jax.device_put(w, NamedSharding(mesh, spec))

        layers = []
        for lp in params["layers"]:
            out = {"in_norm": put(lp["in_norm"], P(None)),
                   "post_norm": put(lp["post_norm"], P(None))}
            for name in ("q", "k", "v", "o", "gate", "up", "down"):
                out[name] = {"w": put(lp[name]["w"], specs[name])}
            layers.append(out)
        sharded = {"embed": put(params["embed"], P(None, None)),
                   "norm": put(params["norm"], P(None)),
                   "layers": layers}
        if "head" in params:
            sharded["head"] = {"w": put(params["head"]["w"],
                                        P(None, None))}
        return sharded

    def _row_proj(self, x, p):
        """The o_proj/down_proj matmul on a tp submesh.  The incoming
        activation is sharded on its feature axis (it is the paired
        column-parallel outputs); plain megatron would contract the
        SPLIT axis per shard and all-reduce the partials — but that
        reassociates the fp32 K-sum and is measurably not bitwise the
        unsharded gemm on this mesh.  Instead both the activation and
        the (in-features-sharded) row weight are constrained replicated
        IN-GRAPH: XLA's sharding algebra inserts all-gathers (pure
        data movement, bit-preserving) and the gemm contracts the full
        K axis exactly like the single-chip engine — the decode-parity
        contract survives sharding bit-for-bit while the weights stay
        sharded at rest (the HBM win) and every upstream matmul stays
        genuinely column-parallel."""
        if self._mesh is None:
            return self._proj(x, p)
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep_x = NamedSharding(self._mesh, P(*([None] * x.ndim)))
        rep_w = NamedSharding(self._mesh, P(None, None))
        x = jax.lax.with_sharding_constraint(x, rep_x)
        w = jax.lax.with_sharding_constraint(p["w"], rep_w)
        return jnp.matmul(x, w.T)

    def _gather_layer(self, lp):
        """Replicate one decode layer's projection weights in-graph.
        Prefill's big gemms stay genuinely column-parallel (full-K
        contractions per output column are bitwise-safe), but decode's
        (B, hid) gemvs are small enough that the partitioner regroups
        them — so the decode/verify graphs gather weights instead
        (decode is bandwidth-bound; the all-gather is bit-preserving
        data movement and the gemv then matches the single-chip
        engine exactly).  Weights stay sharded at rest either way."""
        if self._mesh is None:
            return lp
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(self._mesh, P(None, None))
        out = dict(lp)
        for name in ("q", "k", "v", "o", "gate", "up", "down"):
            p = dict(lp[name])
            p["w"] = jax.lax.with_sharding_constraint(p["w"], rep)
            out[name] = p
        return out

    def _gather_cache(self, ck, cv):
        """Replicate the cache slices a decode step attends over.
        ``_cache_attention`` merges the (sharded) head axis into a
        flat batch axis; left sharded, the partitioner's regrouping of
        that contraction drifts ~1e-7 from the single-chip recurrence.
        An in-graph all-gather is pure data movement, so the attention
        math stays bitwise the unsharded engine's."""
        if self._mesh is None:
            return ck, cv
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(self._mesh, P(*([None] * ck.ndim)))
        return (jax.lax.with_sharding_constraint(ck, rep),
                jax.lax.with_sharding_constraint(cv, rep))

    def _shard_pools(self, kp, vp):
        """Constrain returned pools back to the at-rest kv-head
        sharding so the donated round-trip hands the next dispatch the
        layout its executable was lowered against."""
        if self._mesh is None:
            return kp, vp
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import AXIS_TP
        s = NamedSharding(self._mesh, P(None, None, None, AXIS_TP, None))
        return (jax.lax.with_sharding_constraint(kp, s),
                jax.lax.with_sharding_constraint(vp, s))

    def _shard_scales(self, ks, vs):
        """fp8 scale rows have no kv-head axis (one scalar per token
        row, shared across heads) — they replicate on the submesh."""
        if self._mesh is None:
            return ks, vs
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        s = NamedSharding(self._mesh, P(None, None, None))
        return (jax.lax.with_sharding_constraint(ks, s),
                jax.lax.with_sharding_constraint(vs, s))

    # -- graph building --------------------------------------------------

    @staticmethod
    def _proj(x, p):
        """Dense matmul mirroring the block forwards op-for-op:
        fp32 = FullyConnected's ``x @ w.T``; int8 = QuantizedDense's
        round/clip -> int8 dot_general(int32 accum) -> rescale."""
        import jax.numpy as jnp
        from jax import lax
        if "qw" in p:
            from ..ops.quant_matmul import quantize_rtn_int8
            lead = x.shape[:-1]
            flat = x.reshape(-1, x.shape[-1])
            qx = quantize_rtn_int8(flat, p["as"])
            acc = lax.dot_general(qx, p["qw"], (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (p["as"] *
                                             p["ws"].reshape(1, -1))
            return out.reshape(lead + (out.shape[-1],))
        return jnp.matmul(x, p["w"].T)

    def _head_logits(self, params, x):
        import jax.numpy as jnp
        if "head" in params:
            return self._proj(x, params["head"])
        return jnp.matmul(x, params["embed"].T)

    def _build_prefill(self, bucket):
        """Prefill graph for one prompt padded to ``bucket`` tokens:
        causal forward (the same flash path the full forward runs),
        K/V written into the sequence's blocks, first token sampled from
        the last VALID position's logits."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ..gluon.model_zoo.nlp.llama import (_QPAD, _rms,
                                                 _rot_interleaved)
        from ..ops import quant_kv as _qkv
        from ..ops.flash_attention import flash_attention
        cfg = self.cfg
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        rep, eps, theta = h // kvh, cfg.rms_eps, cfg.rope_theta
        bs = self.block_size
        nb = bucket // bs
        L = bucket

        def body(params, kp, vp, ks, vs, toks, valid, bt, key):
            x = jnp.take(params["embed"], toks, axis=0)      # (1, L, hid)
            pos = jnp.arange(L)
            freqs = theta ** (-jnp.arange(0, d, 2) / d)
            ang = pos[:, None] * freqs[None, :]
            cos, sin = jnp.cos(ang), jnp.sin(ang)
            for li, lp in enumerate(params["layers"]):
                hh = _rms(x, lp["in_norm"], eps)
                q = self._proj(hh, lp["q"]).reshape(1, L, h, d) \
                    .transpose(0, 2, 1, 3)
                k = self._proj(hh, lp["k"]).reshape(1, L, kvh, d) \
                    .transpose(0, 2, 1, 3)
                v = self._proj(hh, lp["v"]).reshape(1, L, kvh, d) \
                    .transpose(0, 2, 1, 3)
                q = _rot_interleaved(q, cos, sin)
                k = _rot_interleaved(k, cos, sin)
                # unrepeated K/V into the pool blocks: (L, kvh, d) rows
                krows = k[0].transpose(1, 0, 2).reshape(nb, bs, kvh, d)
                vrows = v[0].transpose(1, 0, 2).reshape(nb, bs, kvh, d)
                if self._kv_fp8:
                    kq, ksc = _qkv.kv_quantize_fp8(krows)
                    vq, vsc = _qkv.kv_quantize_fp8(vrows)
                    kp = kp.at[li, bt].set(kq)
                    vp = vp.at[li, bt].set(vq)
                    ks = ks.at[li, bt].set(ksc)
                    vs = vs.at[li, bt].set(vsc)
                else:
                    kp = kp.at[li, bt].set(_qkv.kv_cast(krows, kp.dtype))
                    vp = vp.at[li, bt].set(_qkv.kv_cast(vrows, vp.dtype))
                # prefill's OWN attention reads the fresh f32 K/V —
                # quantization touches storage, never this math
                kr = jnp.repeat(k, rep, axis=1)
                vr = jnp.repeat(v, rep, axis=1)
                o = flash_attention(q, kr, vr, causal=True)
                o = o.transpose(0, 2, 1, 3).reshape(1, L, h * d)
                x = x + self._row_proj(o, lp["o"])
                y = _rms(x, lp["post_norm"], eps)
                x = x + self._row_proj(
                    jax.nn.silu(self._proj(y, lp["gate"])) *
                    self._proj(y, lp["up"]), lp["down"])
            x = _rms(x, params["norm"], eps)
            # last-valid-row logits through an M=_QPAD slice (an M=1
            # projection takes XLA's gemv path whose bits differ from
            # the full forward's gemm — see llama._QPAD)
            start = jnp.maximum(valid - _QPAD, 0)
            xs = lax.dynamic_slice_in_dim(x, start, _QPAD, axis=1)
            logits = self._head_logits(params, xs)[0]        # (_QPAD, V)
            last = jnp.take(logits, valid - 1 - start, axis=0)
            tok = self._sample(last[None, :], key)[0]
            kp, vp = self._shard_pools(kp, vp)
            if self._kv_fp8:
                ks, vs = self._shard_scales(ks, vs)
            return last, tok, kp, vp, ks, vs

        if self._kv_fp8:
            return body

        def run(params, kp, vp, toks, valid, bt, key):
            last, tok, kp, vp, _ks, _vs = body(
                params, kp, vp, None, None, toks, valid, bt, key)
            return last, tok, kp, vp

        return run

    def _decode_body(self, params, kp, vp, ks, vs, toks, pos, bts, blk,
                     nbl):
        """One decode step's layer stack, shared by the ``decode`` graph
        and every unrolled ``verify`` step (one source so speculative
        parity cannot drift): embed ``toks`` (B,), rotate at ``pos``,
        scatter K/V into ``blk``/offset, attend through the block
        table, and return (last-norm logits, kp, vp, ks, vs).

        The cache attention routes through
        ``ops.paged_attention.paged_decode_attention`` when
        ``paged_attn`` is set (whose XLA fallback is the inline gather
        below, verbatim) and stays inline otherwise — the kill switch
        compiles the exact PR 7 graph.

        Under fp8 KV storage (ISSUE 20) the scatter quantizes each
        row (amax scale into ``ks``/``vs``) and the gather dequantizes
        before the f32 attention math — the only drift source is the
        storage rounding of PAST tokens' K/V."""
        import jax
        import jax.numpy as jnp
        from ..gluon.model_zoo.nlp.llama import (_cache_attention, _rms,
                                                 _rot_interleaved)
        from ..ops import quant_kv as _qkv
        cfg = self.cfg
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        eps, theta = cfg.rms_eps, cfg.rope_theta
        bs = self.block_size
        B = self.max_batch
        L = nbl * bs
        scale = 1.0 / math.sqrt(d)
        x = jnp.take(params["embed"], toks, axis=0)          # (B, hid)
        freqs = theta ** (-jnp.arange(0, d, 2) / d)
        ang = pos[:, None] * freqs[None, :]                  # (B, d/2)
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        off = pos % bs
        valid = jnp.arange(L)[None, :] <= pos[:, None]       # (B, L)
        for li, lp in enumerate(params["layers"]):
            lp = self._gather_layer(lp)
            hh = _rms(x, lp["in_norm"], eps)
            q = self._proj(hh, lp["q"]).reshape(B, h, d)
            k = self._proj(hh, lp["k"]).reshape(B, kvh, d)
            v = self._proj(hh, lp["v"]).reshape(B, kvh, d)
            q = _rot_interleaved(q, cos[:, None, :], sin[:, None, :])
            k = _rot_interleaved(k, cos[:, None, :], sin[:, None, :])
            if self._kv_fp8:
                kq, ksc = _qkv.kv_quantize_fp8(k)
                vq, vsc = _qkv.kv_quantize_fp8(v)
                kp = kp.at[li, blk, off].set(kq)
                vp = vp.at[li, blk, off].set(vq)
                ks = ks.at[li, blk, off].set(ksc)
                vs = vs.at[li, blk, off].set(vsc)
            else:
                kp = kp.at[li, blk, off].set(_qkv.kv_cast(k, kp.dtype))
                vp = vp.at[li, blk, off].set(_qkv.kv_cast(v, vp.dtype))
            if self.paged_attn:
                from ..ops.paged_attention import paged_decode_attention
                kpl, vpl = self._gather_cache(kp[li], vp[li])
                if self._kv_fp8:
                    o = paged_decode_attention(q, kpl, vpl, bts, pos,
                                               scale, k_scale=ks[li],
                                               v_scale=vs[li])
                else:
                    o = paged_decode_attention(q, kpl, vpl, bts, pos,
                                               scale)
            else:
                ck = kp[li][bts].reshape(B, L, kvh, d)
                cv = vp[li][bts].reshape(B, L, kvh, d)
                if self._kv_fp8:
                    ck = _qkv.kv_dequantize(
                        ck, ks[li][bts].reshape(B, L))
                    cv = _qkv.kv_dequantize(
                        cv, vs[li][bts].reshape(B, L))
                elif self.kv_dtype is not None:
                    ck = _qkv.kv_dequantize(ck)
                    cv = _qkv.kv_dequantize(cv)
                ck = ck.transpose(0, 2, 1, 3)                # (B,kvh,L,d)
                cv = cv.transpose(0, 2, 1, 3)
                ck, cv = self._gather_cache(ck, cv)
                o = _cache_attention(q, ck, cv, valid, scale)
            x = x + self._row_proj(o, lp["o"])
            y = _rms(x, lp["post_norm"], eps)
            x = x + self._row_proj(
                jax.nn.silu(self._proj(y, lp["gate"])) *
                self._proj(y, lp["up"]), lp["down"])
        logits = self._head_logits(params, _rms(x, params["norm"], eps))
        kp, vp = self._shard_pools(kp, vp)
        if self._kv_fp8:
            ks, vs = self._shard_scales(ks, vs)
        return logits, kp, vp, ks, vs

    def _build_decode(self, nbl):
        """One-token decode for the fixed batch against ``nbl`` gathered
        blocks per sequence (context bucket = nbl * block_size)."""
        import jax.numpy as jnp
        bs = self.block_size

        def body(params, kp, vp, ks, vs, toks, pos, bts, active, key):
            blk = jnp.take_along_axis(
                bts, (pos // bs)[:, None], axis=1)[:, 0]     # (B,)
            blk = jnp.where(active, blk, 0)                  # null block
            logits, kp, vp, ks, vs = self._decode_body(
                params, kp, vp, ks, vs, toks, pos, bts, blk, nbl)
            return logits, self._sample(logits, key), kp, vp, ks, vs

        if self._kv_fp8:
            return body

        def run(params, kp, vp, toks, pos, bts, active, key):
            logits, tok, kp, vp, _ks, _vs = body(
                params, kp, vp, None, None, toks, pos, bts, active, key)
            return logits, tok, kp, vp

        return run

    def _build_verify(self, size):
        """Speculative verify graph: ``W`` decode steps unrolled in ONE
        dispatch (size = (W, nbl)).  Row i feeds its last committed
        token then its draft continuation at positions
        ``pos[i] .. pos[i] + counts[i] - 1``; step ``w`` scatters that
        token's K/V (visible to step ``w+1`` through the functional
        kp/vp threading) and argmaxes the next token.  Steps past a
        row's count write to the null block and their outputs are
        host-masked — a count-1 row is bitwise a plain decode row.

        Greedy-only by construction: acceptance is exact token
        equality against these argmaxes, so every accepted position's
        computation is identical to the plain decode path's and the
        committed stream is bitwise the non-speculative stream (the
        ISSUE 17 acceptance contract)."""
        import jax.numpy as jnp
        W, nbl = size
        bs = self.block_size

        def body(params, kp, vp, ks, vs, toks, pos, bts, counts, active,
                 key):
            outs = []
            for w in range(W):
                live = active & (w < counts)                 # (B,)
                pw = pos + w
                blk = jnp.take_along_axis(
                    bts, jnp.clip(pw // bs, 0, nbl - 1)[:, None],
                    axis=1)[:, 0]
                blk = jnp.where(live, blk, 0)                # null block
                logits, kp, vp, ks, vs = self._decode_body(
                    params, kp, vp, ks, vs, toks[:, w], pw, bts, blk,
                    nbl)
                outs.append(jnp.argmax(logits, axis=-1)
                            .astype(jnp.int32))
            return jnp.stack(outs, axis=1), kp, vp, ks, vs   # (B, W)

        if self._kv_fp8:
            return body

        def run(params, kp, vp, toks, pos, bts, counts, active, key):
            out, kp, vp, _ks, _vs = body(params, kp, vp, None, None,
                                         toks, pos, bts, counts, active,
                                         key)
            return out, kp, vp

        return run

    def _build_chunk_prefill(self, nbl):
        """Packed continuation prefill: up to ``max_batch`` rows, each a
        chunk of up to ``prefill_chunk`` prompt tokens starting at an
        arbitrary position, attending to ``nbl`` gathered blocks of that
        row's cache (offset-causal: key position <= query position).

        The attention is ``ops.flash_attention._scan_forward`` with the
        row index replaced by the ABSOLUTE position — same block
        decomposition, same einsum specs, same ``-1e30`` mask constant,
        same normalization order — so a chunk row's output (and the K/V
        it scatters) is bitwise the cold full-prefill's row for the
        same tokens (the prefix-cache parity gate,
        tests/test_serving_frontend.py)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ..gluon.model_zoo.nlp.llama import _rms, _rot_interleaved
        from ..ops.flash_attention import _NEG_INF, _pick_block
        cfg = self.cfg
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        rep, eps, theta = h // kvh, cfg.rms_eps, cfg.rope_theta
        bs = self.block_size
        R, C = self.max_batch, self.prefill_chunk
        L = nbl * bs
        bk = _pick_block(L, 256) or L
        nk = L // bk
        scale = 1.0 / math.sqrt(d)

        def attend(q, kr, vr, qpos):
            # q (R*h, C, d); kr/vr (R*h, L, d); qpos (R*h, C) absolute
            kb = kr.reshape(R * h, nk, bk, d).transpose(1, 0, 2, 3)
            vb = vr.reshape(R * h, nk, bk, d).transpose(1, 0, 2, 3)

            def step(carry, blk):
                acc, m_i, l_i, j = carry
                kj, vj = blk
                s = jnp.einsum("bqd,bkd->bqk", q, kj,
                               preferred_element_type=jnp.float32) * scale
                kpos = j * bk + lax.broadcasted_iota(jnp.int32, (C, bk), 1)
                s = jnp.where(qpos[:, :, None] >= kpos[None], s, _NEG_INF)
                m_new = jnp.maximum(m_i, jnp.max(s, axis=-1,
                                                 keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_i - m_new)
                l_new = l_i * alpha + jnp.sum(p, axis=-1, keepdims=True)
                acc = acc * alpha + jnp.einsum(
                    "bqk,bkd->bqd", p.astype(vr.dtype), vj,
                    preferred_element_type=jnp.float32)
                return (acc, m_new, l_new, j + 1), None

            init = (jnp.zeros((R * h, C, d), jnp.float32),
                    jnp.full((R * h, C, 1), _NEG_INF, jnp.float32),
                    jnp.zeros((R * h, C, 1), jnp.float32),
                    jnp.int32(0))
            (acc, m_i, l_i, _), _ = lax.scan(step, init, (kb, vb))
            return (acc / jnp.maximum(l_i, 1e-30)).astype(q.dtype)

        def body(params, kp, vp, ks, vs, toks, starts, valids, bts,
                 active, key):
            from ..ops import quant_kv as _qkv
            x = jnp.take(params["embed"], toks, axis=0)      # (R, C, hid)
            cidx = jnp.arange(C)
            abs_pos = starts[:, None] + cidx[None, :]        # (R, C)
            freqs = theta ** (-jnp.arange(0, d, 2) / d)
            ang = abs_pos[..., None] * freqs
            cos, sin = jnp.cos(ang), jnp.sin(ang)            # (R, C, d/2)
            write = active[:, None] & (cidx[None, :] < valids[:, None])
            blk = jnp.take_along_axis(
                bts, jnp.clip(abs_pos // bs, 0, nbl - 1), axis=1)
            blk = jnp.where(write, blk, 0)                   # null block
            off = abs_pos % bs
            qpos = jnp.repeat(abs_pos, h, axis=0)            # (R*h, C)
            for li, lp in enumerate(params["layers"]):
                hh = _rms(x, lp["in_norm"], eps)
                q = self._proj(hh, lp["q"]).reshape(R, C, h, d) \
                    .transpose(0, 2, 1, 3)
                k = self._proj(hh, lp["k"]).reshape(R, C, kvh, d) \
                    .transpose(0, 2, 1, 3)
                v = self._proj(hh, lp["v"]).reshape(R, C, kvh, d)
                q = _rot_interleaved(q, cos[:, None], sin[:, None])
                k = _rot_interleaved(k, cos[:, None], sin[:, None])
                krows = k.transpose(0, 2, 1, 3)              # (R,C,kvh,d)
                if self._kv_fp8:
                    kq, ksc = _qkv.kv_quantize_fp8(krows)
                    vq, vsc = _qkv.kv_quantize_fp8(v)
                    kp = kp.at[li, blk, off].set(kq)
                    vp = vp.at[li, blk, off].set(vq)
                    ks = ks.at[li, blk, off].set(ksc)
                    vs = vs.at[li, blk, off].set(vsc)
                else:
                    kp = kp.at[li, blk, off].set(
                        _qkv.kv_cast(krows, kp.dtype))
                    vp = vp.at[li, blk, off].set(
                        _qkv.kv_cast(v, vp.dtype))
                ck = kp[li][bts].reshape(R, L, kvh, d)
                cv = vp[li][bts].reshape(R, L, kvh, d)
                if self._kv_fp8:
                    ck = _qkv.kv_dequantize(
                        ck, ks[li][bts].reshape(R, L))
                    cv = _qkv.kv_dequantize(
                        cv, vs[li][bts].reshape(R, L))
                elif self.kv_dtype is not None:
                    ck = _qkv.kv_dequantize(ck)
                    cv = _qkv.kv_dequantize(cv)
                ck = ck.transpose(0, 2, 1, 3)                # (R,kvh,L,d)
                cv = cv.transpose(0, 2, 1, 3)
                kr = jnp.repeat(ck, rep, axis=1).reshape(R * h, L, d)
                vr = jnp.repeat(cv, rep, axis=1).reshape(R * h, L, d)
                o = attend(q.reshape(R * h, C, d), kr, vr, qpos)
                o = o.reshape(R, h, C, d).transpose(0, 2, 1, 3) \
                    .reshape(R, C, h * d)
                x = x + self._row_proj(o, lp["o"])
                y = _rms(x, lp["post_norm"], eps)
                x = x + self._row_proj(
                    jax.nn.silu(self._proj(y, lp["gate"])) *
                    self._proj(y, lp["up"]), lp["down"])
            x = _rms(x, params["norm"], eps)
            logits = self._head_logits(params, x)            # (R, C, V)
            last = jnp.take_along_axis(
                logits, jnp.clip(valids - 1, 0, C - 1)[:, None, None],
                axis=1)[:, 0]                                # (R, V)
            kp, vp = self._shard_pools(kp, vp)
            if self._kv_fp8:
                ks, vs = self._shard_scales(ks, vs)
            return last, self._sample(last, key), kp, vp, ks, vs

        if self._kv_fp8:
            return body

        def run(params, kp, vp, toks, starts, valids, bts, active, key):
            last, nxt, kp, vp, _ks, _vs = body(
                params, kp, vp, None, None, toks, starts, valids, bts,
                active, key)
            return last, nxt, kp, vp

        return run

    def _build_cow(self, _size):
        """Copy-on-write block fork: duplicate one physical block's K/V
        (all layers) into a freshly allocated block, pools donated.
        Under fp8 KV the per-row amax scales ride along — a forked
        block must dequantize identically to its source."""
        if self._kv_fp8:
            def run_fp8(kp, vp, ks, vs, src, dst):
                kp, vp = self._shard_pools(
                    kp.at[:, dst].set(kp[:, src]),
                    vp.at[:, dst].set(vp[:, src]))
                ks, vs = self._shard_scales(
                    ks.at[:, dst].set(ks[:, src]),
                    vs.at[:, dst].set(vs[:, src]))
                return kp, vp, ks, vs
            return run_fp8

        def run(kp, vp, src, dst):
            return self._shard_pools(kp.at[:, dst].set(kp[:, src]),
                                     vp.at[:, dst].set(vp[:, src]))
        return run

    def _sample(self, logits, key):
        """In-graph next-token sampling: greedy at temperature 0, else
        (top-k) categorical — logits never leave the device per token."""
        import jax
        import jax.numpy as jnp
        if self.temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / self.temperature
        if self.top_k > 0:
            vals, idx = jax.lax.top_k(scaled, self.top_k)
            pick = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(
                idx, pick[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, scaled,
                                      axis=-1).astype(jnp.int32)

    # -- compile cache (the retrace-detector discipline) -----------------

    def _sig(self, kind, size):
        # paged_attn is part of the signature: the routing changes the
        # compiled graph body, so a SHARED cache (Router fleets) must
        # never hand a paged executable to an inline engine or back.
        # The mesh spec rides too (ISSUE 18): a tp-sharded executable
        # bakes its input shardings in, so a shared cache must never
        # serve it to an engine on a different submesh.
        # kv_dtype rides too (ISSUE 20): the fp8 graphs take the scale
        # planes as extra donated args, so a shared cache must never
        # hand an fp8 executable to a full-precision engine or back.
        return (kind, size, self.cache.num_blocks, self.max_batch,
                self.block_size, self.paged_attn,
                self.mesh_config.describe(), self.kv_dtype)

    def _get(self, kind, size, args):
        """Compile-cache lookup keyed by (kind, shape-signature); every
        miss is one AOT compile (``jit(...).lower(args).compile()``) and
        is COUNTED — serving traffic after warmup() must never miss.
        The cached object is a fixed executable, so an unexpected
        shape/dtype drift raises loudly instead of retracing silently
        (the PR 1 retrace-detector discipline, enforced not observed).
        The signature carries the pool geometry so a SHARED cache
        (Router fleets) only ever serves executables whose donated pool
        shapes match this engine's."""
        sig = self._sig(kind, size)
        fn = self._compiled.get(sig)
        if fn is None:
            import jax
            tc0 = _trace.clock() if _trace.enabled() else None
            build = {"prefill": self._build_prefill,
                     "decode": self._build_decode,
                     "chunk": self._build_chunk_prefill,
                     "verify": self._build_verify,
                     "cow": self._build_cow}[kind](size)
            if self._kv_fp8:
                # scale planes are donated alongside the pools: fp8 cow
                # is run(kp, vp, ks, vs, src, dst); the other families
                # take (params, kp, vp, ks, vs, ...)
                donate = (0, 1, 2, 3) if kind == "cow" else (1, 2, 3, 4)
            else:
                donate = (0, 1) if kind == "cow" else (1, 2)
            fn = jax.jit(build, donate_argnums=donate) \
                .lower(*args).compile()
            self._compiled[sig] = fn
            self.stats["compiles"] += 1
            _telem.inc("serving.compiles")
            # verify sizes are (width, n_blocks) tuples; keep ints for
            # the scalar families (existing telemetry schema)
            sz = int(size) if isinstance(size, int) else str(size)
            if tc0 is not None:
                # compiles on the request timeline: a warmup-miss that
                # stalls traffic is visible exactly where it hurt
                _trace.record("engine.compile", tc0, _trace.clock(),
                              kind=kind, size=sz)
            if self._warmed:
                # the tier-1 zero-retrace assertion reads the engine's
                # own counter; the registry twin is what a live scrape
                # sees (one source of truth for bench/loadgen, ISSUE 9)
                self.stats["compiles_after_warmup"] += 1
                _telem.inc("serving.compiles_after_warmup")
                _telem.event("serving.compile_after_warmup",
                             kind=kind, size=sz)
        return fn

    def _verify_widths(self):
        """Compiled verify widths: the power-of-two buckets covering up
        to ``spec_k + 1`` fed tokens (last committed + drafts), floor 2
        — a 1-token boundary uses the plain decode graph instead."""
        top = 2
        while top < self.spec_k + 1:
            top *= 2
        out, w = [], 2
        while w <= top:
            out.append(w)
            w *= 2
        return out

    def warmup(self):
        """AOT-compile every (prefill, decode[, chunk, cow]) bucket
        graph by running each once against the real pools (compile +
        execute warms the jit cache; the pools round-trip through the
        donated call).  Graphs already present in a SHARED compile
        cache (Router replicas) are skipped outright — the fleet
        compiles each signature once."""
        import jax
        dummy_key = jax.random.key(0)
        for bucket in self.buckets:
            nb = bucket // self.block_size
            if self._sig("prefill", bucket) in self._compiled and \
                    self._sig("decode", nb) in self._compiled:
                continue
            ok = self.cache.alloc("__warmup__", bucket)
            if not ok:
                raise MXNetError("warmup: KV pool too small for bucket "
                                 f"{bucket}; raise num_blocks")
            bt = _np.asarray(self.cache.table("__warmup__"), _np.int32)
            toks = _np.zeros((1, bucket), _np.int32)
            args = (self.params,) + self.cache.pool_args() + \
                (toks, _np.int32(1), bt, dummy_key)
            out = self._get("prefill", bucket, args)(*args)
            self.cache.update_pools(
                *out[2:], site="InferenceEngine.warmup(prefill)")
            bts = self.cache.table_array(
                ["__warmup__"] + [None] * (self.max_batch - 1), nb)
            args = (self.params,) + self.cache.pool_args() + \
                (_np.zeros((self.max_batch,), _np.int32),
                 _np.zeros((self.max_batch,), _np.int32), bts,
                 _np.zeros((self.max_batch,), bool), dummy_key)
            out = self._get("decode", nb, args)(*args)
            self.cache.update_pools(
                *out[2:], site="InferenceEngine.warmup(decode)")
            self.cache.free("__warmup__")
        if self.prefill_chunk:
            # the packed-chunk family: one graph per context bucket,
            # warmed with every row inactive (all writes land in the
            # null block, so no pool allocation is needed)
            R, C = self.max_batch, self.prefill_chunk
            for bucket in self.buckets:
                nb = bucket // self.block_size
                if self._sig("chunk", nb) in self._compiled:
                    continue
                args = (self.params,) + self.cache.pool_args() + \
                    (_np.zeros((R, C), _np.int32),
                     _np.zeros((R,), _np.int32),
                     _np.zeros((R,), _np.int32),
                     _np.zeros((R, nb), _np.int32),
                     _np.zeros((R,), bool), dummy_key)
                out = self._get("chunk", nb, args)(*args)
                self.cache.update_pools(
                    *out[2:], site="InferenceEngine.warmup(chunk)")
        if self.spec_decode:
            # the speculative verify family: one graph per (width,
            # context bucket), warmed all-inactive like the chunk family
            # (dead rows write the null block — no pool allocation)
            B = self.max_batch
            for W in self._verify_widths():
                for bucket in self.buckets:
                    nb = bucket // self.block_size
                    if self._sig("verify", (W, nb)) in self._compiled:
                        continue
                    args = (self.params,) + self.cache.pool_args() + \
                        (_np.zeros((B, W), _np.int32),
                         _np.zeros((B,), _np.int32),
                         _np.zeros((B, nb), _np.int32),
                         _np.zeros((B,), _np.int32),
                         _np.zeros((B,), bool), dummy_key)
                    out = self._get("verify", (W, nb), args)(*args)
                    self.cache.update_pools(
                        *out[1:], site="InferenceEngine.warmup(verify)")
        if self.prefill_chunk or self.prefix_cache is not None:
            if self._sig("cow", 0) not in self._compiled:
                # the copy-on-write block copy (src=dst=0 copies the
                # null block onto itself — garbage by design)
                args = self.cache.pool_args() + \
                    (_np.int32(0), _np.int32(0))
                out = self._get("cow", 0, args)(*args)
                self.cache.update_pools(
                    *out, site="InferenceEngine.warmup(cow)")
        self._warmed = True
        return self

    # -- serving calls ---------------------------------------------------

    def prefill(self, slot, tokens):
        """Prefill ``tokens`` (1D int sequence) into ``slot``: allocates
        blocks, runs the bucketed prefill graph, samples the first
        generated token.  Returns ``(first_token, last_logits)`` or None
        when the prompt exceeds max_context or the pool is exhausted
        (request stays queued)."""
        import jax
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        t = toks.shape[0]
        if t == 0:
            raise MXNetError("prefill needs at least one token")
        bucket = next_bucket(t, self.buckets)
        if bucket is None:
            return None
        if not self.cache.alloc(slot, bucket):
            return None
        padded = _np.zeros((1, bucket), _np.int32)
        padded[0, :t] = toks
        bt = _np.asarray(self.cache.table(slot), _np.int32)
        key = jax.random.fold_in(self._base_key,
                                 (1 << 30) + self.stats["prefill_calls"])
        args = (self.params,) + self.cache.pool_args() + \
            (padded, _np.int32(t), bt, key)
        t0 = _telem.clock() if _telem.enabled() else None
        out = self._get("prefill", bucket, args)(*args)
        last, tok = out[0], out[1]
        self.cache.update_pools(*out[2:], site="InferenceEngine.prefill")
        self.cache.trim(slot, t)
        self.cache.set_len(slot, t)
        self.stats["prefill_calls"] += 1
        self.stats["prompt_tokens_computed"] += t
        if t0 is not None:
            _telem.inc("serving.prefill_calls")
            _telem.observe("serving.prefill_ms",
                           (_telem.clock() - t0) * 1e3)
            self._publish_cache_gauges()
        return int(tok), last

    def attach_prefix(self, slot, tokens):
        """Prefix-cache admission: adopt the longest cached block chain
        that prefixes ``tokens`` into ``slot`` (refcounts bumped, zero
        compute) and return the number of cached positions (0 = miss or
        no prefix cache; the caller prefills from there)."""
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.attach(slot, tokens)

    def insert_prefix(self, slot, tokens):
        """Register ``slot``'s freshly prefilled prompt in the prefix
        cache so later requests sharing the prefix skip its compute."""
        if self.prefix_cache is not None:
            self.prefix_cache.insert(slot, tokens)

    def pin_prefix(self, tokens):
        """Prefill ``tokens`` ONCE into a temporary slot and pin the
        chain — including the partial tail block — in the prefix cache.
        The deliberate system-prompt seam: every later request starting
        with ``tokens`` adopts the blocks (CoW on its first write past
        them) instead of recomputing.  Returns False when the pool
        cannot hold the prefix right now."""
        if self.prefix_cache is None:
            raise MXNetError("pin_prefix needs prefix_cache=True")
        # id(self) namespaces the pin against OTHER engines on a shared
        # pool (disaggregated fleet): two replicas pinning their first
        # prefix must not collide on the same slot key
        slot = ("__prefix_pin__", id(self), self.stats["prefill_calls"])
        if self.prefill(slot, tokens) is None:
            return False
        self.prefix_cache.insert(slot, tokens)
        self.release(slot)
        return True

    def chunk_prefill(self, entries):
        """One PACKED continuation-prefill dispatch (the ISSUE 12
        chunked/batched prefill): ``entries`` is a list of
        ``(slot, tokens, start)`` rows — ``tokens`` (<= prefill_chunk of
        them) are the prompt positions ``[start, start+n)`` of ``slot``,
        whose table already caches everything before ``start``.

        Allocates/CoW-forks the written blocks, runs the compiled chunk
        graph once for ALL rows, and returns ``(next_tokens, logits)``
        aligned with ``entries`` (row meaningful only for rows whose
        chunk ends the prompt).  Returns None when the pool cannot
        cover the chunk (callers may evict prefix chains and retry)."""
        import jax
        if not self.prefill_chunk:
            raise MXNetError("chunk_prefill needs prefill_chunk > 0 "
                             "(MXTPU_PREFILL_CHUNK)")
        n = len(entries)
        if not 1 <= n <= self.max_batch:
            raise MXNetError(f"chunk_prefill: {n} rows vs max_batch "
                             f"{self.max_batch}")
        C = self.prefill_chunk
        end_max = 0
        for slot, toks, start in entries:
            t = len(toks)
            if not 1 <= t <= C:
                raise MXNetError(f"chunk of {t} tokens vs chunk bucket "
                                 f"{C}")
            if not self.cache.ensure(slot, start + t - 1):
                return None
            copies = self.cache.prepare_write(slot, start, start + t)
            if copies is None:
                return None
            self._apply_cow(copies)
            end_max = max(end_max, start + t)
        bucket = next_bucket(end_max, self.buckets)
        if bucket is None:
            raise MXNetError(f"chunk end {end_max} exceeds max_context "
                             f"{self.max_context}")
        nbl = bucket // self.block_size
        R = self.max_batch
        toks = _np.zeros((R, C), _np.int32)
        starts = _np.zeros((R,), _np.int32)
        valids = _np.zeros((R,), _np.int32)
        active = _np.zeros((R,), bool)
        slots = [None] * R
        for i, (slot, chunk, start) in enumerate(entries):
            toks[i, :len(chunk)] = _np.asarray(chunk, _np.int32)
            starts[i], valids[i], active[i] = start, len(chunk), True
            slots[i] = slot
        bts = self.cache.table_array(slots, nbl)
        key = jax.random.fold_in(self._base_key,
                                 (1 << 29) +
                                 self.stats["chunk_prefill_calls"])
        args = (self.params,) + self.cache.pool_args() + \
            (toks, starts, valids, bts, active, key)
        t0 = _telem.clock() if _telem.enabled() else None
        out = self._get("chunk", nbl, args)(*args)
        last, nxt = out[0], out[1]
        self.cache.update_pools(*out[2:],
                                site="InferenceEngine.chunk_prefill")
        for slot, chunk, start in entries:
            self.cache.set_len(slot, start + len(chunk))
        self.stats["chunk_prefill_calls"] += 1
        self.stats["prompt_tokens_computed"] += \
            int(sum(len(c) for _s, c, _p in entries))
        if t0 is not None:
            _telem.inc("serving.chunk_prefill_calls")
            _telem.observe("serving.chunk_prefill_ms",
                           (_telem.clock() - t0) * 1e3)
            self._publish_cache_gauges()
        return _np.asarray(nxt)[:n], _np.asarray(last)[:n]

    def _apply_cow(self, copies):
        """Run the device half of each (src -> dst) copy-on-write fork
        the cache planned: the new block must carry the shared block's
        bits before the caller's write lands."""
        for src, dst in copies:
            args = self.cache.pool_args() + \
                (_np.int32(src), _np.int32(dst))
            out = self._get("cow", 0, args)(*args)
            self.cache.update_pools(*out,
                                    site="InferenceEngine._apply_cow")

    def _publish_cache_gauges(self):
        _telem.set_gauge("serving.kv_block_utilization",
                         round(self.cache.utilization(), 4))
        _telem.set_gauge("serving.kv_blocks_in_use",
                         self.cache.blocks_in_use)
        # memory honesty (ISSUE 15): exact bytes the live block-table
        # entries pin, so an OOM post-mortem names the KV pool by size
        _telem.set_gauge("serving.kv_bytes_in_use",
                         self.cache.blocks_in_use
                         * self.cache.block_nbytes)
        if self.prefix_cache is not None:
            hr = self.prefix_cache.hit_rate()
            if hr is not None:
                _telem.set_gauge("serving.prefix_hit_rate",
                                 round(hr, 4))

    def reserve(self, slot, pos, n=1):
        """Grow ``slot``'s block table to cover positions
        ``[pos, pos + n)`` before a decode/verify step,
        copy-on-write-forking written blocks a prefix chain still
        shares.  ``n > 1`` is the speculative write-ahead: the verify
        graph scatters the whole draft window before acceptance is
        known (rejected positions stay garbage until ``trim``).  Under
        pool pressure, LRU prefix chains are evicted first (only chains
        — never a block a live sequence holds); False when the pool is
        exhausted even then."""
        pc = self.prefix_cache
        last = pos + n - 1
        if not self.cache.ensure(slot, last):
            need = self.cache.blocks_for(last + 1) - \
                len(self.cache.table(slot))
            if pc is None or not pc.evict(blocks_needed=need):
                return False
            if not self.cache.ensure(slot, last):
                return False
        copies = self.cache.prepare_write(slot, pos, pos + n)
        if copies is None:
            if pc is None or not pc.evict(blocks_needed=1):
                return False
            copies = self.cache.prepare_write(slot, pos, pos + n)
            if copies is None:
                return False
        self._apply_cow(copies)
        return True

    def decode(self, entries):
        """One decode step for the joined batch.

        entries: list of (slot, token, position) for the ACTIVE rows
        (position = where this token goes, i.e. current sequence
        length).  Pads to the fixed batch, picks the context bucket from
        the max position, gathers block tables, runs the compiled step.
        Returns (next_tokens (n_active,) np.int32, logits rows).
        """
        import jax
        if not entries:
            raise MXNetError("decode: empty batch")
        n = len(entries)
        if n > self.max_batch:
            raise MXNetError(f"decode batch {n} > max_batch")
        max_pos = max(p for _, _, p in entries)
        bucket = next_bucket(max_pos + 1, self.buckets)
        if bucket is None:
            raise MXNetError(f"position {max_pos} exceeds max_context "
                             f"{self.max_context}")
        nbl = bucket // self.block_size
        slots = [s for s, _, _ in entries] + \
            [None] * (self.max_batch - n)
        toks = _np.zeros((self.max_batch,), _np.int32)
        pos = _np.zeros((self.max_batch,), _np.int32)
        active = _np.zeros((self.max_batch,), bool)
        for i, (slot, tok, p) in enumerate(entries):
            toks[i], pos[i], active[i] = tok, p, True
            self.cache.set_len(slot, p + 1)
        bts = self.cache.table_array(slots, nbl)
        key = jax.random.fold_in(self._base_key,
                                 self.stats["decode_calls"])
        args = (self.params,) + self.cache.pool_args() + \
            (toks, pos, bts, active, key)
        t0 = _telem.clock() if _telem.enabled() else None
        out = self._get("decode", nbl, args)(*args)
        logits, nxt = out[0], out[1]
        self.cache.update_pools(*out[2:], site="InferenceEngine.decode")
        self.stats["decode_calls"] += 1
        if t0 is not None:
            _telem.inc("serving.decode_calls")
            _telem.observe("serving.decode_ms",
                           (_telem.clock() - t0) * 1e3)
            self._publish_cache_gauges()
        nxt = _np.asarray(nxt)[:n]
        return nxt, _np.asarray(logits)[:n]

    def verify(self, entries):
        """One speculative verify dispatch (ISSUE 17).

        entries: list of ``(slot, tokens, position)`` — ``tokens`` is
        the row's last committed token followed by its draft
        continuation (1 <= len <= spec_k + 1), fed at positions
        ``position .. position + len - 1``.  The caller must have
        :meth:`reserve`\\ d that whole range.  Returns ``out``
        (n_active, W) np.int32 where ``out[i, j]`` is the greedy token
        after absorbing ``tokens[i][:j+1]`` — the caller commits the
        prefix of drafts that match and trims the write-ahead past the
        committed length (see ContinuousBatcher._decode_spec)."""
        import jax
        if not entries:
            raise MXNetError("verify: empty batch")
        if self.temperature != 0.0:
            raise NotSupportedError(
                "verify is greedy-only; sampled decoding keeps the "
                "plain decode path")
        n = len(entries)
        if n > self.max_batch:
            raise MXNetError(f"verify batch {n} > max_batch")
        wmax = max(len(t) for _, t, _ in entries)
        if wmax < 1:
            raise MXNetError("verify: empty token row")
        if wmax > self.spec_k + 1:
            raise MXNetError(f"verify row of {wmax} tokens vs spec_k "
                             f"{self.spec_k} (+1 committed)")
        W = next_bucket(max(wmax, 2), self._verify_widths())
        end_max = max(p + len(t) for _, t, p in entries)
        bucket = next_bucket(end_max, self.buckets)
        if bucket is None:
            raise MXNetError(f"verify end {end_max} exceeds "
                             f"max_context {self.max_context}")
        nbl = bucket // self.block_size
        slots = [s for s, _, _ in entries] + \
            [None] * (self.max_batch - n)
        toks = _np.zeros((self.max_batch, W), _np.int32)
        pos = _np.zeros((self.max_batch,), _np.int32)
        counts = _np.zeros((self.max_batch,), _np.int32)
        active = _np.zeros((self.max_batch,), bool)
        for i, (slot, tk, p) in enumerate(entries):
            tk = _np.asarray(tk, _np.int32).reshape(-1)
            toks[i, :tk.shape[0]] = tk
            pos[i], counts[i], active[i] = p, tk.shape[0], True
            # write-ahead length; the scheduler trims back to the
            # committed length after acceptance
            self.cache.set_len(slot, p + tk.shape[0])
        bts = self.cache.table_array(slots, nbl)
        key = jax.random.fold_in(self._base_key,
                                 (1 << 28) + self.stats["verify_calls"])
        args = (self.params,) + self.cache.pool_args() + \
            (toks, pos, bts, counts, active, key)
        t0 = _telem.clock() if _telem.enabled() else None
        res = self._get("verify", (W, nbl), args)(*args)
        out = res[0]
        self.cache.update_pools(*res[1:], site="InferenceEngine.verify")
        self.stats["verify_calls"] += 1
        self.stats["draft_tokens_scored"] += \
            int(sum(len(t) - 1 for _, t, _ in entries))
        if t0 is not None:
            _telem.inc("serving.verify_calls")
            _telem.observe("serving.verify_ms",
                           (_telem.clock() - t0) * 1e3)
            self._publish_cache_gauges()
        return _np.asarray(out)[:n]

    def release(self, slot):
        """Finished sequence: drop its hold on its blocks (a block a
        prefix chain still references survives in the pool)."""
        self.cache.free(slot)
        if _telem.enabled():
            self._publish_cache_gauges()
