"""Continuous-batching request scheduler over the compiled engine.

The serving loop the north star asks for: requests arrive on a queue,
new sequences JOIN the running decode batch at token boundaries and
finished ones vacate their slot in the same boundary — the decode batch
never drains to admit work (continuous batching), unlike the static
discipline where a batch is formed once and every slot waits for the
slowest member.

Prefill/decode split: prompts run through the engine's bucketed prefill
graphs as separate calls BETWEEN decode steps (at most
``prefills_per_step`` per boundary, so one long prompt delays the
running batch by a bounded amount instead of stalling it for a whole
generation).  ``StaticBatcher`` implements the fixed-batch baseline over
the SAME engine so the load generator's continuous-vs-static comparison
measures the scheduling policy, not two different compiled paths.

Chunked prefill (ISSUE 12): when the engine's ``prefill_chunk`` is set,
admission switches from one-prompt-per-dispatch to PACKED chunks — every
boundary gathers up to ``max_batch`` rows (tail chunks of in-flight long
prompts first, then new admissions, each first consulting the prefix
cache so a cached system prompt costs zero compute) into ONE
``chunk_prefill`` dispatch.  Same work, strictly fewer dispatches than
the one-per-boundary policy on any mixed queue — the deterministic gate
in tests/test_serving_frontend.py.  A long prompt still delays the
running batch by at most one chunk per boundary.

Speculative decoding (ISSUE 17): with the engine's ``spec_decode`` set,
the decode boundary becomes draft -> verify -> accept.  A model-free
:class:`~.draft.DraftSource` proposes up to ``spec_k`` continuation
tokens per row (prefix-cache trie walk, then prompt-lookup n-gram); ONE
``engine.verify`` dispatch scores every row's last committed token plus
its drafts; the greedy-matching draft prefix is committed (1..K+1
tokens per boundary from one dispatch) and the paged-KV write-ahead
past the committed length is trimmed.  Acceptance is exact token
equality against the verify argmaxes, so the committed stream is
BITWISE the non-speculative greedy stream — speculation changes
dispatch count, never output (tests/test_spec_decode.py).  A sequence
whose drafts keep missing stops drafting for a cooldown window
(per-sequence fallback — it rides the same dispatch as a plain 1-token
row), and a boundary where no row drafts runs the plain decode graph.

Disaggregated prefill/decode (ISSUE 18): a batcher can be built with a
``role`` — ``"prefill"`` admits prompts and parks the finished-prefill
requests in a ``handoff_ready`` outbox instead of decoding them;
``"decode"`` never admits from its queue and instead ``adopt_handoff``\ s
requests whose KV blocks were filled by a prefill-role peer over the
SAME :class:`~.kv_cache.PagedKVCache`.  The handoff rides the CoW
refcount machinery: the decode side refs every block FIRST (adopt), the
prefill side releases its slot SECOND (``complete_handoff``, which
insists every block still shows the adopter's hold) — a crash between
the two leaves blocks over-held (requeue-able), never freed early.
Engines over a shared pool namespace their slots (``slot_ns``) so slot
keys cannot collide.  Protocol violations raise the typed
:class:`~.kv_cache.HandoffError`.

Everything here is host-side policy: per-token device work is exactly
one compiled decode step; the only host pull per boundary is the sampled
token vector (needed to detect EOS and admit/evict — the serving
analogue of HB10's one-sync-per-window rule).
"""
from __future__ import annotations

import itertools
import time
from collections import deque

from ..base import MXNetError
from .. import telemetry as _telem
from ..telemetry import tracing as _trace
from ..telemetry import watchdog as _watchdog
from .draft import DraftSource
from .kv_cache import HandoffError

__all__ = ["Request", "ContinuousBatcher", "StaticBatcher"]

_ids = itertools.count()


class Request:
    """One generation request: ``tokens`` (prompt ids), ``max_new_tokens``
    and an optional per-request ``eos_id``."""

    def __init__(self, tokens, max_new_tokens, eos_id=None, request_id=None):
        self.id = next(_ids) if request_id is None else request_id
        self.tokens = [int(t) for t in tokens]
        if not self.tokens:
            raise MXNetError("Request needs at least one prompt token")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        # lifecycle stamps (perf_counter seconds) + outputs
        self.submit_t = None
        self.first_token_t = None
        self.finish_t = None
        self.generated = []
        self.finish_reason = None     # "eos" | "length"
        # causal tracing (ISSUE 14): the root span of this request's
        # life — created at first admission, SURVIVES a drain/requeue
        # hop (the requeued chain parents under the same root)
        self.trace = None
        self._queue_t0 = None         # current queue-residency start

    @property
    def done(self):
        return self.finish_reason is not None

    def latency(self):
        if self.submit_t is None or self.finish_t is None:
            return None
        return self.finish_t - self.submit_t

    def ttft(self):
        """Time to first token."""
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def tpot(self):
        """Time per output token AFTER the first (the decode-pool
        latency signal the autoscaler scales on; None until a second
        token exists to measure)."""
        if (self.first_token_t is None or self.finish_t is None
                or len(self.generated) < 2):
            return None
        return (self.finish_t - self.first_token_t) \
            / (len(self.generated) - 1)


class _BatcherBase:
    def __init__(self, engine, slot_ns=None, role="combined"):
        if role not in ("combined", "prefill", "decode"):
            raise MXNetError(f"batcher role {role!r} must be "
                             "combined|prefill|decode")
        self.engine = engine
        # slot namespace: engines sharing one PagedKVCache (the
        # disaggregated fleet) must not collide on slot keys — slots
        # are opaque hashables, so a namespaced slot is (ns, i)
        self.slot_ns = slot_ns
        self.role = role
        # prefill-role outbox: requests whose prompt is fully cached in
        # a slot THIS batcher still owns, awaiting block handoff to a
        # decode-role peer (the router drains it every boundary)
        self.handoff_ready = deque()
        self.queue = deque()
        self.finished = []
        # per-boundary occupancy samples: active slots / max_batch
        self.occupancy_samples = []
        self.decode_steps = 0
        self.tokens_generated = 0
        # speculative accounting (stays zero on non-speculative runs)
        self.verify_steps = 0
        self.spec_drafted = 0
        self.spec_accepted = 0

    def submit(self, request):
        request.submit_t = time.perf_counter()
        if _trace.enabled():
            if request.trace is None:
                request.trace = _trace.start("request", id=request.id)
            request._queue_t0 = _trace.clock()
        self.queue.append(request)
        return request

    # -- shared helpers --------------------------------------------------

    def _admit_one(self, slot, req):
        """Prefill ``req`` into ``slot``; returns True on admission.
        The first generated token comes from the prefill itself."""
        tp0 = _trace.clock() if _trace.enabled() else None
        out = self.engine.prefill(slot, req.tokens)
        if out is None:
            return False
        if tp0 is not None:
            # admission succeeded: queue residency ends where the
            # prefill begins; both parent under the request root
            if req._queue_t0 is not None:
                _trace.record("queue", req._queue_t0, tp0,
                              parent=req.trace)
                req._queue_t0 = None
            _trace.record("prefill", tp0, _trace.clock(),
                          parent=req.trace, slot=slot,
                          tokens=len(req.tokens))
        tok, _logits = out
        req.first_token_t = time.perf_counter()
        if _telem.enabled() and req.submit_t is not None:
            _telem.observe("serving.ttft_ms",
                           (req.first_token_t - req.submit_t) * 1e3)
        self._append_token(req, slot, tok)
        return True

    def _append_token(self, req, slot, tok):
        req.generated.append(int(tok))
        self.tokens_generated += 1
        _telem.inc("serving.tokens_generated")
        if req.eos_id is not None and int(tok) == int(req.eos_id):
            req.finish_reason = "eos"
        elif len(req.generated) >= req.max_new_tokens:
            req.finish_reason = "length"
        if req.done:
            req.finish_t = time.perf_counter()
            self.engine.release(slot)
            self.finished.append(req)
            _trace.finish(req.trace, reason=req.finish_reason,
                          tokens=len(req.generated))
            if _telem.enabled():
                _telem.inc("serving.requests_finished")
                lat = req.latency()
                if lat is not None:
                    _telem.observe("serving.request_latency_ms",
                                   lat * 1e3)

    def _decode_active(self, active):
        """One joined decode step over ``active`` {slot: request}."""
        td0 = _trace.clock() if _trace.enabled() else None
        entries = []
        for slot, req in active.items():
            pos = len(req.tokens) + len(req.generated) - 1
            # the token AT ``pos`` is the last generated one; its K/V is
            # written by this step, so the table must cover ``pos``
            if not self.engine.reserve(slot, pos):
                raise MXNetError("KV pool exhausted mid-decode; raise "
                                 "num_blocks or lower max_batch")
            entries.append((slot, req.generated[-1], pos))
        nxt, _logits = self.engine.decode(entries)
        self.decode_steps += 1
        self.occupancy_samples.append(len(entries) / self.engine.max_batch)
        if td0 is not None:
            # one joined dispatch, one span PER REQUEST (same [t0,t1],
            # each parented under its own request root): every request's
            # chain carries all N of its decode boundaries
            td1 = _trace.clock()
            for slot, _t, pos in entries:
                _trace.record("decode", td0, td1,
                              parent=active[slot].trace, pos=pos)
        if _telem.enabled():
            # per-boundary scheduler state: what a live scrape of a
            # serving pod needs to spot admission stalls (ISSUE 9)
            _telem.set_gauge("serving.queue_depth", len(self.queue))
            _telem.observe("serving.batch_occupancy",
                           len(entries) / self.engine.max_batch,
                           edges=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                                  0.875, 1.0))
            _telem.inc("serving.decode_steps")
        if _watchdog.enabled():
            # the serving health rules tick at the same boundary seam
            # (host ints only — queue saturation + KV-leak trend)
            _watchdog.on_serving_boundary(
                queue_depth=len(self.queue),
                kv_blocks_in_use=self.engine.cache.blocks_in_use)
        for (slot, _t, _p), tok in zip(entries, nxt):
            self._append_token(active[slot], slot, tok)
        for slot in [s for s, r in active.items() if r.done]:
            del active[slot]

    def occupancy(self):
        s = self.occupancy_samples
        return sum(s) / len(s) if s else None

    def stats(self):
        lat = sorted(r.latency() for r in self.finished
                     if r.latency() is not None)

        def pct(p):
            if not lat:
                return None
            return lat[min(len(lat) - 1, int(round(p * (len(lat) - 1))))]

        return {"requests": len(self.finished),
                "tokens_generated": self.tokens_generated,
                "decode_steps": self.decode_steps,
                "verify_steps": self.verify_steps,
                "spec_accept_rate": (
                    round(self.spec_accepted / self.spec_drafted, 4)
                    if self.spec_drafted else None),
                "tokens_per_dispatch": (
                    round(self.tokens_generated / self.decode_steps, 4)
                    if self.decode_steps else None),
                "occupancy": (round(self.occupancy(), 4)
                              if self.occupancy() is not None else None),
                "p50_latency_s": pct(0.50), "p99_latency_s": pct(0.99),
                "cache": self.engine.cache.stats()}


class _PrefillState:
    """A prompt part-way through chunked prefill: ``done`` positions of
    ``req.tokens`` are cached in ``slot`` (prefix-cache hits count)."""

    __slots__ = ("req", "slot", "done")

    def __init__(self, req, slot, done):
        self.req = req
        self.slot = slot
        self.done = int(done)


class ContinuousBatcher(_BatcherBase):
    """Token-boundary continuous batching: admit into free slots before
    every decode step, evict finished sequences the moment EOS/length
    hits, never drain the batch to take new work.  With the engine's
    ``prefill_chunk`` set, admission packs chunks from several prompts
    into one dispatch per boundary (ISSUE 12 chunked prefill)."""

    # boundaries a sequence sits out after its drafts stop landing
    # (deterministic host counter; re-probes when it expires)
    _spec_cooldown = 8
    _spec_miss_limit = 2

    def __init__(self, engine, prefills_per_step=1, speculative=None,
                 spec_k=None, slot_ns=None, role="combined"):
        super().__init__(engine, slot_ns=slot_ns, role=role)
        self.prefills_per_step = int(prefills_per_step)
        self.active = {}          # slot -> Request
        self.prefilling = {}      # slot -> _PrefillState (chunked only)
        self._free_slots = [self._slot(i)
                            for i in range(engine.max_batch - 1, -1, -1)]
        # speculative decoding (ISSUE 17): defaults follow the engine
        # (which reads MXTPU_SPEC_DECODE / MXTPU_SPEC_K)
        self.speculative = engine.spec_decode if speculative is None \
            else bool(speculative)
        if self.speculative and not engine.spec_decode:
            raise MXNetError(
                "speculative batching needs an engine built with "
                "spec_decode=True (the verify graphs compile at warmup)")
        self.spec_k = engine.spec_k if spec_k is None else int(spec_k)
        if not 1 <= self.spec_k <= engine.spec_k:
            raise MXNetError(f"spec_k {self.spec_k} outside the "
                             f"engine's compiled [1, {engine.spec_k}]")
        self.draft = DraftSource(prefix_cache=engine.prefix_cache)
        self._spec_state = {}     # req.id -> [misses, cooldown]

    def _slot(self, i):
        """Slot keys are opaque hashables end-to-end (engine, cache,
        traces); a namespaced batcher mints ``(ns, i)`` so two engines
        over one SHARED pool can never collide."""
        return i if self.slot_ns is None else (self.slot_ns, i)

    def _stage_or_activate(self, slot, req):
        """A freshly prefilled, unfinished request either joins the
        decode batch (combined role) or parks in the handoff outbox —
        the slot and its blocks stay owned by THIS batcher until a
        decode-role peer adopts them (adopt-then-release)."""
        if self.role == "prefill":
            self.handoff_ready.append((slot, req))
        else:
            self.active[slot] = req

    def adopt_handoff(self, req, blocks, n_tokens):
        """Decode-role entry seam: adopt a prefilled request whose KV
        ``blocks`` (covering ``n_tokens`` positions) live in the SHARED
        pool.  Each block gains a holder BEFORE the prefill side drops
        its own (the adopt-then-release protocol — a crash between the
        two leaves blocks over-held, never freed early).  Returns the
        new slot, or None when no batch slot is free (backpressure:
        the entry stays in the prefill outbox)."""
        if self.role != "decode":
            raise HandoffError(
                f"adopt_handoff on a {self.role!r}-role batcher — only "
                "decode-role replicas adopt prefill handoffs")
        if not self._free_slots:
            return None
        slot = self._free_slots[-1]
        self.engine.cache.adopt(slot, blocks, n_tokens)
        self._free_slots.pop()
        self.active[slot] = req
        return slot

    def complete_handoff(self, slot):
        """Prefill-role exit seam: release ``slot`` AFTER the decode
        side adopted its blocks.  Every block must still show the
        adopter's hold (refcount >= 2) — releasing sole-held blocks
        here would free live KV mid-handoff, the exact leak class the
        typed error names."""
        cache = self.engine.cache
        for blk in cache.table(slot):
            if cache.refcount(blk) < 2:
                raise HandoffError(
                    f"complete_handoff({slot!r}): block {blk} has "
                    f"{cache.refcount(blk)} holder(s) — the decode side "
                    "must adopt before the prefill side releases")
        self.engine.release(slot)
        self._free_slots.append(slot)

    def step(self):
        """One scheduling boundary: admit queued requests (one packed
        chunk dispatch when chunked, else up to ``prefills_per_step``
        single-prompt prefills), then run one joined decode step.
        Returns the amount of work done — admissions + prefill rows +
        sequences decoded (0 means the boundary was a no-op)."""
        if self.engine.prefill_chunk:
            admitted = self._admit_chunked()
        else:
            admitted = self._admit_serial()
        if self.role == "prefill":
            # the prefill pool's saturation signal: admissions this
            # boundary + prompts mid-chunk, over the batch (TTFT
            # pressure makes the autoscaler grow THIS pool)
            self.occupancy_samples.append(min(
                1.0, (admitted + len(self.prefilling))
                / self.engine.max_batch))
            return admitted
        if not self.active:
            return admitted
        before = set(self.active)
        if self.speculative:
            self._decode_spec(self.active)
        else:
            self._decode_active(self.active)
        for slot in before - set(self.active):
            self._free_slots.append(slot)
        return admitted + len(before)

    def _decode_spec(self, active):
        """One speculative boundary: draft, verify in ONE dispatch,
        commit the greedy-matching prefix, trim the write-ahead.

        Bitwise contract: a committed token is either a verify argmax
        (computed by the decode body op-for-op) or a draft that EQUALED
        one — so the generated stream is exactly the plain greedy
        stream, only produced in fewer dispatches.  A boundary where no
        row drafts (cold caches, cooldowns, length caps) delegates to
        the plain decode graph."""
        eng = self.engine
        drafts = {}
        any_draft = False
        for slot, req in active.items():
            pos = len(req.tokens) + len(req.generated) - 1
            st = self._spec_state.get(req.id)
            if st is not None and st[1] > 0:
                st[1] -= 1        # cooling down: ride as a plain row
                drafts[slot] = []
                continue
            # a draft may commit up to cap+1 tokens and write K/V up to
            # pos+cap; both the length budget and the context ceiling
            # (next boundary writes pos+committed) bound the window
            cap = min(self.spec_k,
                      req.max_new_tokens - len(req.generated) - 1,
                      eng.max_context - 2 - pos)
            d = self.draft.propose(req.tokens + req.generated, cap) \
                if cap > 0 else []
            drafts[slot] = d
            if d:
                any_draft = True
        if not any_draft:
            return self._decode_active(active)
        td0 = _trace.clock() if _trace.enabled() else None
        entries = []
        for slot, req in active.items():
            pos = len(req.tokens) + len(req.generated) - 1
            toks = [req.generated[-1]] + drafts[slot]
            if len(toks) > 1 and not eng.reserve(slot, pos, len(toks)):
                toks = toks[:1]   # pool pressure: shed the write-ahead
            if len(toks) == 1 and not eng.reserve(slot, pos):
                raise MXNetError("KV pool exhausted mid-decode; raise "
                                 "num_blocks or lower max_batch")
            entries.append((slot, toks, pos))
        out = eng.verify(entries)
        self.decode_steps += 1
        self.verify_steps += 1
        self.occupancy_samples.append(len(entries) / eng.max_batch)
        td1 = _trace.clock() if td0 is not None else None
        for i, (slot, toks, pos) in enumerate(entries):
            req = active[slot]
            D = len(toks) - 1
            # out[i, j] = greedy token after absorbing toks[:j+1]; the
            # drafts matching their predecessor's argmax are accepted,
            # each match's own argmax rides along as the next commit
            committed = [int(out[i, 0])]
            j = 1
            while j <= D and int(toks[j]) == int(out[i, j - 1]):
                committed.append(int(out[i, j]))
                j += 1
            accepted = j - 1
            self.spec_drafted += D
            self.spec_accepted += accepted
            if D:
                st = self._spec_state.setdefault(req.id, [0, 0])
                if accepted == 0:
                    st[0] += 1
                    if st[0] >= self._spec_miss_limit:
                        st[0], st[1] = 0, self._spec_cooldown
                else:
                    st[0] = 0
            if td0 is not None:
                _trace.record("verify", td0, td1, parent=req.trace,
                              pos=pos, drafted=D, accepted=accepted)
            for tok in committed:
                if req.done:
                    break         # EOS inside the window: rest is moot
                self._append_token(req, slot, tok)
            if req.done:
                self._spec_state.pop(req.id, None)
            else:
                # roll back the write-ahead: K/V past the committed
                # length is rejected-draft garbage — drop the length
                # and any blocks only the garbage covered
                n = pos + 1 + accepted
                eng.cache.trim(slot, n)
                eng.cache.set_len(slot, n)
        if _telem.enabled():
            _telem.set_gauge("serving.queue_depth", len(self.queue))
            _telem.observe("serving.batch_occupancy",
                           len(entries) / eng.max_batch,
                           edges=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                                  0.875, 1.0))
            _telem.inc("serving.decode_steps")
            if self.spec_drafted:
                _telem.set_gauge(
                    "serving.spec_accept_rate",
                    round(self.spec_accepted / self.spec_drafted, 4))
        if _watchdog.enabled():
            _watchdog.on_serving_boundary(
                queue_depth=len(self.queue),
                kv_blocks_in_use=eng.cache.blocks_in_use)
        for slot in [s for s, r in active.items() if r.done]:
            del active[slot]

    def _admit_serial(self):
        admitted = 0
        while (self.queue and self._free_slots
               and admitted < self.prefills_per_step):
            slot = self._free_slots[-1]
            req = self.queue[0]
            if not self._admit_one(slot, req):
                break                       # pool full / prompt too long
            self.queue.popleft()
            self._free_slots.pop()
            admitted += 1
            if req.done:                    # finished inside prefill
                self._free_slots.append(slot)
            else:
                self._stage_or_activate(slot, req)
        return admitted

    def _admit_chunked(self):
        """Pack one ``chunk_prefill`` dispatch: tail chunks of in-flight
        prompts first (they hold slots and blocks — finish them), then
        new admissions through the prefix cache.  Returns admissions +
        dispatched rows."""
        eng = self.engine
        C = eng.prefill_chunk
        entries, rows = [], {}
        for slot, st in list(self.prefilling.items()):
            if len(entries) >= eng.max_batch:
                break
            chunk = st.req.tokens[st.done:st.done + C]
            entries.append((slot, chunk, st.done))
            rows[slot] = st
        admitted = 0
        while (self.queue and self._free_slots
               and len(entries) < eng.max_batch):
            req = self.queue[0]
            if len(req.tokens) - 1 >= eng.max_context:
                raise MXNetError(
                    "request cannot be admitted (prompt exceeds "
                    "max_context)")
            slot = self._free_slots[-1]
            start = eng.attach_prefix(slot, req.tokens)
            if start == 0 and not eng.cache.alloc(slot, 0):
                break                       # cannot even open a table
            self.queue.popleft()
            self._free_slots.pop()
            if _trace.enabled() and req._queue_t0 is not None:
                _trace.record("queue", req._queue_t0, _trace.clock(),
                              parent=req.trace, prefix_hit=start)
                req._queue_t0 = None
            st = _PrefillState(req, slot, start)
            self.prefilling[slot] = st
            entries.append((slot, req.tokens[start:start + C], start))
            rows[slot] = st
            admitted += 1
        if not entries:
            return admitted
        tc0 = _trace.clock() if _trace.enabled() else None
        out = eng.chunk_prefill(entries)
        if out is None and eng.prefix_cache is not None:
            # pool pressure: evict LRU chains no request still shares
            # (refcount > 1 blocks survive untouched), then retry once
            need = sum(
                max(0, eng.cache.blocks_for(start + len(chunk))
                    - len(eng.cache.table(slot)))
                for slot, chunk, start in entries)
            if eng.prefix_cache.evict(blocks_needed=need):
                out = eng.chunk_prefill(entries)
        if out is None:
            # still starved: in-flight prompts keep their state and
            # retry next boundary (decode frees blocks as requests end)
            return admitted
        nxt, _logits = out
        if tc0 is not None:
            # one packed dispatch, one span per packed ROW — each
            # chunk parents under its own request's root
            tc1 = _trace.clock()
            for slot, chunk, start in entries:
                _trace.record("prefill_chunk", tc0, tc1,
                              parent=rows[slot].req.trace, slot=slot,
                              start=start, tokens=len(chunk))
        for i, (slot, chunk, start) in enumerate(entries):
            st = rows[slot]
            st.done = start + len(chunk)
            if st.done < len(st.req.tokens):
                continue                    # more chunks to come
            del self.prefilling[slot]
            req = st.req
            req.first_token_t = time.perf_counter()
            if _telem.enabled() and req.submit_t is not None:
                _telem.observe("serving.ttft_ms",
                               (req.first_token_t - req.submit_t) * 1e3)
            # register the finished prompt BEFORE decode writes past it
            # (the partial tail block CoW-forks on the first write)
            eng.insert_prefix(slot, req.tokens)
            self._append_token(req, slot, int(nxt[i]))
            if req.done:
                self._free_slots.append(slot)
            else:
                self._stage_or_activate(slot, req)
        return admitted + len(entries)

    def run(self, max_steps=100000):
        """Drive until queue and batch are empty."""
        if self.role != "combined":
            raise MXNetError(
                f"run() drives a combined-role batcher; a "
                f"{self.role!r}-role batcher only makes progress under "
                "a Router that drains its handoffs")
        steps = 0
        while self.queue or self.active or self.prefilling:
            moved = self.step()
            steps += 1
            if steps > max_steps:
                raise MXNetError("run() exceeded max_steps — scheduler "
                                 "wedged (pool too small for any "
                                 "queued request?)")
            if moved == 0 and not self.active and \
                    (self.queue or self.prefilling):
                # a no-op boundary with work still queued: the head
                # request can never be admitted
                raise MXNetError(
                    "request cannot be admitted (prompt exceeds "
                    "max_context or KV pool too small)")
        return self.stats()


class StaticBatcher(_BatcherBase):
    """The fixed-batch baseline: form a batch of up to ``max_batch``
    requests, prefill them all, decode until EVERY member finishes
    (finished slots idle — their decode rows are wasted), then form the
    next batch.  Same engine, same graphs; only the policy differs."""

    def run(self, max_steps=100000):
        steps = 0
        while self.queue:
            n_before = len(self.queue)
            active = {}
            for slot in range(self.engine.max_batch):
                if not self.queue:
                    break
                req = self.queue[0]
                if not self._admit_one(slot, req):
                    break
                self.queue.popleft()
                if not req.done:
                    active[slot] = req
            if len(self.queue) == n_before:
                # nothing could be admitted into an EMPTY batch: the
                # head request can never run
                raise MXNetError(
                    "request cannot be admitted (prompt exceeds "
                    "max_context or KV pool too small)")
            while active:
                # occupancy decays as members finish: the finished
                # slots' rows ride every remaining decode step unused —
                # the waste continuous batching exists to reclaim
                self._decode_active(active)
                steps += 1
                if steps > max_steps:
                    raise MXNetError("static run exceeded max_steps")
        return self.stats()
