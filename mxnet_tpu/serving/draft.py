"""Model-free draft sources for speculative decoding (ISSUE 17).

Speculative decoding needs candidate continuations CHEAPER than a model
dispatch; a second (smaller) draft model would cost HBM and its own
compile family.  This repo already holds two free sources of likely
continuations:

- the :class:`~.frontend.prefix_cache.PrefixCache` trie — every prompt
  (and pinned system prompt) ever inserted is a token chain keyed by
  its exact prefix, so "what did earlier traffic say after these exact
  tokens?" is one refcount-neutral trie walk
  (``PrefixCache.continuation``);
- the request's OWN context — prompt-lookup / n-gram self-match
  (summarization, code editing, RAG: the generation repeats spans of
  the prompt), the classic zero-model draft.

A draft is a GUESS: the engine's ``verify`` graph scores it against the
real model in one dispatch, and the scheduler commits exactly the
greedy-matching prefix — so a bad draft costs nothing but the wasted
verify rows, never correctness (the bitwise-greedy acceptance contract,
docs/SERVING.md §Speculative decoding).

Everything here is host-side integer work at token boundaries — no
device dispatches, no allocations in the KV pool, deterministic for a
given context (the chaos drain/requeue replay depends on that).
"""
from __future__ import annotations

__all__ = ["DraftSource"]


class DraftSource:
    """Propose up to ``k`` continuation tokens for a request context.

    Parameters
    ----------
    prefix_cache : optional PrefixCache whose trie is consulted first
        (its chains come from real traffic and beat self-matches when
        present); None = prompt-lookup only.
    ngram : longest trailing n-gram tried for the prompt-lookup
        self-match (falls through to shorter grams down to 1).
    """

    def __init__(self, prefix_cache=None, ngram=3):
        self.prefix_cache = prefix_cache
        self.ngram = max(1, int(ngram))
        # accounting (host ints; the scheduler publishes rates)
        self.proposals = 0
        self.from_cache = 0
        self.from_ngram = 0

    def propose(self, context, k):
        """Up to ``k`` draft tokens continuing ``context`` (the
        request's prompt + generated so far).  Empty list = nothing to
        speculate on (the scheduler then decodes plainly)."""
        k = int(k)
        if k <= 0 or len(context) < 2:
            return []
        out = []
        if self.prefix_cache is not None:
            out = self.prefix_cache.continuation(context, k)
            if out:
                self.from_cache += 1
        if not out:
            out = self._ngram_match(context, k)
            if out:
                self.from_ngram += 1
        if out:
            self.proposals += 1
        return [int(t) for t in out[:k]]

    def _ngram_match(self, context, k):
        """Prompt-lookup decoding: find the most recent EARLIER
        occurrence of the trailing n-gram in the context and propose
        the tokens that followed it (longest gram wins, then recency —
        deterministic)."""
        ctx = [int(t) for t in context]
        top = min(self.ngram, len(ctx) - 1)
        for n in range(top, 0, -1):
            tail = ctx[-n:]
            # the tail itself starts at len(ctx)-n; scan strictly
            # earlier starts, most recent first
            for i in range(len(ctx) - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    cont = ctx[i + n:i + n + k]
                    if cont:
                        return cont
        return []
