"""Base utilities: errors, registry plumbing, dtype handling.

TPU-native rebuild of MXNet's base layer. In the reference these concerns live
in ``python/mxnet/base.py`` (ctypes bridge, ``check_call``, ``MXNetError``) and
``src/c_api/c_api_error.cc``. Here there is no C ABI: the framework is
Python+JAX down to XLA, so ``base`` keeps only the error type, the op/block
registries, and dtype utilities.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "NotSupportedError", "string_types",
           "numeric_types", "integer_types", "registry_create", "DTYPE_MAP"]


class MXNetError(RuntimeError):
    """Error raised by the framework.

    Mirrors ``mxnet.base.MXNetError`` (reference: python/mxnet/base.py), which
    re-raised C++ ``dmlc::Error`` across the C ABI. Here errors propagate
    natively, so this is a plain Python exception with the same name so user
    ``except mx.MXNetError`` code keeps working.
    """


class NotSupportedError(MXNetError):
    """A coherent request the current build deliberately does not serve
    yet.  Distinct from a misuse error: the message names the tracked
    follow-up that lifts the limit, so callers can feature-gate on the
    TYPE instead of pattern-matching message strings."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Canonical dtype names accepted across the API (reference: mshadow type enum
# mapping in python/mxnet/base.py _DTYPE_NP_TO_MX / _DTYPE_MX_TO_NP).
DTYPE_MAP = {
    "float32": _np.float32,
    "float64": _np.float64,
    "float16": _np.float16,
    "bfloat16": "bfloat16",  # resolved lazily via ml_dtypes/jnp
    "uint8": _np.uint8,
    "int8": _np.int8,
    "int32": _np.int32,
    "int64": _np.int64,
    "bool": _np.bool_,
}


def registry_create(nickname):
    """Create a (register, create, get_registry) triple for named factories.

    Stands in for the reference's ``mxnet.registry`` module
    (python/mxnet/registry.py) which backed ``mx.init.@register``,
    ``mx.optimizer.register`` etc.
    """
    registry = {}

    def register(klass_or_name=None, name=None):
        def _do(klass, reg_name):
            key = (reg_name or klass.__name__).lower()
            registry[key] = klass
            return klass

        if isinstance(klass_or_name, str):
            # used as @register("name")
            return lambda klass: _do(klass, klass_or_name)
        if klass_or_name is None:
            return lambda klass: _do(klass, name)
        return _do(klass_or_name, name)

    def create(spec, *args, **kwargs):
        if isinstance(spec, str):
            key = spec.lower()
            if key not in registry:
                raise MXNetError(
                    f"Cannot find {nickname} '{spec}'. "
                    f"Registered: {sorted(registry)}")
            return registry[key](*args, **kwargs)
        return spec

    return register, create, registry
