"""Async checkpointing: training never blocks on the filesystem.

Reference context (SURVEY.md §5.4): the reference's recovery story is
"checkpoint every epoch and restart" with synchronous `mx.nd.save`. The
TPU-idiomatic upgrade (orbax-style async checkpoint) splits the save into
(a) a device->host snapshot started immediately (async D2H — the step
stream keeps running) and (b) serialization + atomic file rename on a
background thread. `save_checkpoint_async` returns a ticket; the NEXT save
(or `wait()`) joins the previous write, bounding the number of in-flight
checkpoints to one — the same discipline orbax uses.
"""
from __future__ import annotations

import os
import threading

import jax

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray import utils as nd_utils

__all__ = ["AsyncCheckpointer", "save_checkpoint_async"]


class _Ticket:
    def __init__(self):
        self._done = threading.Event()
        self._error = None
        self.path = None

    def wait(self, timeout=None):
        """Block until the write is durable; re-raises writer errors."""
        if not self._done.wait(timeout):
            raise MXNetError("checkpoint write timed out")
        if self._error is not None:
            raise self._error
        return self.path


class AsyncCheckpointer:
    """One in-flight checkpoint at a time, written off-thread.

    Usage::

        ckpt = AsyncCheckpointer()
        for epoch in ...:
            train_epoch()
            ckpt.save(f"model-{epoch:04d}.params", net_params_dict)
        ckpt.wait_until_finished()
    """

    def __init__(self):
        self._current = None   # (thread, ticket)
        self._lock = threading.Lock()

    def save(self, fname, arrays):
        """Snapshot ``arrays`` (name -> NDArray) and write them to
        ``fname`` in the background. Returns a ticket with ``.wait()``.

        The device buffers are snapshotted BEFORE returning (async D2H
        copies are started; jax arrays are immutable so the values are
        consistent even while training continues); only the host-side
        serialization happens on the thread.
        """
        # start non-blocking D2H for every array; immutability makes this
        # a consistent snapshot of "now"
        snap = {}
        for k, v in arrays.items():
            a = v.data if isinstance(v, NDArray) else v
            try:
                a.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
            # wrap the captured IMMUTABLE jax array, never the caller's
            # mutable handle — later `w += ...` on the handle must not
            # leak into this snapshot
            snap[k] = NDArray(a)

        self.wait_until_finished()      # at most one write in flight
        ticket = _Ticket()

        def write():
            tmp = fname + ".tmp"
            try:
                nd_utils.save(tmp, snap)
                os.replace(tmp, fname)  # atomic: readers never see a torn file
                ticket.path = fname
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                ticket._error = MXNetError(
                    f"async checkpoint to {fname} failed: "
                    f"{type(e).__name__}: {e}")
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            finally:
                ticket._done.set()

        t = threading.Thread(target=write, daemon=True,
                             name="mxtpu-ckpt-writer")
        with self._lock:
            self._current = (t, ticket)
        t.start()
        return ticket

    def wait_until_finished(self, timeout=None):
        with self._lock:
            cur = self._current
            self._current = None
        if cur is not None:
            thread, ticket = cur
            try:
                ticket.wait(timeout)
            except MXNetError:
                # writer still running (timeout): keep tracking it so the
                # next save() joins it instead of racing a second writer
                # onto the same .tmp path
                if not ticket._done.is_set():
                    with self._lock:
                        if self._current is None:
                            self._current = cur
                raise
        return True


_DEFAULT = AsyncCheckpointer()


def save_checkpoint_async(fname, arrays):
    """Module-level convenience over a shared AsyncCheckpointer."""
    return _DEFAULT.save(fname, arrays)
