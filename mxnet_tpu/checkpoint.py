"""Preemption-safe checkpointing: full training state, crash-consistent.

Reference context (SURVEY.md §5.4): the reference's recovery story is
"checkpoint every epoch and restart" with synchronous `mx.nd.save` of
bare params.  At pod scale preemption is the steady state, not the
exception (arXiv 1909.09756; arXiv 2011.03641 treat restartability as a
precondition for multi-hour runs), and bare params are not enough: a
SIGTERM mid-epoch must not lose the optimizer state, the lr/update
counters, the data-iterator position, or the RNG stream.

Three layers live here:

:class:`AsyncCheckpointer`
    orbax-style async array writes — device->host snapshot started
    immediately, serialization + atomic rename on a background thread,
    at most one write in flight.

:class:`CheckpointManager`
    full-training-state checkpoints as crash-consistent directories:
    per-array CRC32s and a JSON manifest written LAST via ``os.replace``
    (a crash at any byte leaves either the previous manifest or none —
    never a half-trusted checkpoint), retention (``keep=N``), and
    :meth:`~CheckpointManager.latest` that validates and SKIPS torn or
    corrupt checkpoints instead of restoring garbage.

:class:`PreemptionHandler` / :func:`run_preemptible`
    SIGTERM/SIGINT turn into a cooperative "finish the in-flight step,
    force-sync a final checkpoint, exit cleanly" flag instead of a
    mid-step kill.

Checkpoint layout (``<dir>/ckpt-<step:08d>/``)::

    params.ndz      model parameters          (mx.nd container format)
    trainer.ndz     optimizer state arrays    (per-parameter space —
                                               dp-independent, see
                                               docs/FAULT_TOLERANCE.md)
    rng.ndz         mx PRNG key + numpy MT state
    manifest.json   step/epoch/cursor/counters + per-file and per-array
                    CRC32s; written last, atomically

Env knobs: ``MXTPU_CKPT_KEEP`` (retention, default 3),
``MXTPU_CKPT_ASYNC=0`` (force synchronous saves),
``MXTPU_CKPT_TIMEOUT`` (seconds ``wait_until_finished`` blocks before
raising :class:`CheckpointTimeout`; default: forever).
"""
from __future__ import annotations

import json
import os
import re
import shutil
import signal as _signal
import threading
import time
import zlib

import numpy as _np
import jax

from .base import MXNetError
from .lint import racecheck as _racecheck
from .ndarray.ndarray import NDArray
from .ndarray import utils as nd_utils
from .testing import faults as _faults
from . import telemetry as _telem

__all__ = ["AsyncCheckpointer", "save_checkpoint_async", "CheckpointManager",
           "CheckpointTimeout", "PreemptionHandler", "run_preemptible",
           "reshard_in_place", "reshard_from_checkpoint"]


class CheckpointTimeout(MXNetError):
    """``wait()`` gave up before the writer finished — the write may
    still complete; distinguishable from a writer *failure* (which
    raises the writer's own wrapped error)."""


class _Ticket:
    def __init__(self, desc=""):
        self._done = threading.Event()
        self._error = None
        self._desc = desc
        self.path = None

    def wait(self, timeout=None):
        """Block until the write is durable; re-raises writer errors.
        A timeout raises :class:`CheckpointTimeout` (the write is still
        in flight); a writer failure raises the wrapped error."""
        if not self._done.wait(timeout):
            raise CheckpointTimeout(
                f"checkpoint write {self._desc or self.path} still in "
                f"flight after {timeout}s")
        if self._error is not None:
            raise self._error
        return self.path


class AsyncCheckpointer:
    """One in-flight checkpoint at a time, written off-thread.

    Usage::

        ckpt = AsyncCheckpointer()
        for epoch in ...:
            train_epoch()
            ckpt.save(f"model-{epoch:04d}.params", net_params_dict)
        ckpt.wait_until_finished()
    """

    def __init__(self):
        self._current = None   # (thread, ticket)
        self._lock = _racecheck.make_lock("AsyncCheckpointer._lock")

    def save(self, fname, arrays):
        """Snapshot ``arrays`` (name -> NDArray) and write them to
        ``fname`` in the background. Returns a ticket with ``.wait()``.

        The device buffers are snapshotted BEFORE returning (async D2H
        copies are started; jax arrays are immutable so the values are
        consistent even while training continues); only the host-side
        serialization happens on the thread.

        A failure of the *previous* write does not swallow this one:
        the new write is started first, then the old error is re-raised
        (with the new ticket attached as ``.pending_ticket``) so the
        caller both learns about the lost snapshot and keeps the fresh
        one going.
        """
        snap = _snapshot(arrays)

        def write():
            tmp = fname + ".tmp"
            try:
                _faults.fault_point("checkpoint.write", fname)
                nd_utils.save(tmp, snap)
                os.replace(tmp, fname)  # atomic: no torn file visible
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            return fname

        return self._submit(write, desc=fname)

    def _submit(self, job, desc=""):
        """Shared writer-thread discipline: join the previous write
        first (at most one in flight), start ``job`` on a fresh thread,
        and surface — without swallowing the new write — any error the
        previous writer died with."""
        prev_error = None
        try:
            self.wait_until_finished()
        except CheckpointTimeout:
            # previous writer still RUNNING: starting a second one would
            # race it onto the same paths — nothing started, re-raise
            raise
        except MXNetError as e:
            # previous writer FAILED: that snapshot is lost, but this
            # one must not be — start it, then surface the old error
            prev_error = e
        ticket = _Ticket(desc)
        # ISSUE 14: the writer thread's span parents under the trace
        # that requested the save (capture here, activate on the thread)
        from .telemetry import tracing as _tracing
        trace_ctx = _tracing.capture()

        def run():
            try:
                with _tracing.activate(trace_ctx):
                    t0 = _tracing.clock() if _tracing.enabled() else None
                    ticket.path = job()
                    if t0 is not None:
                        _tracing.record("checkpoint.async_write", t0,
                                        _tracing.clock(), path=desc)
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                ticket._error = MXNetError(
                    f"async checkpoint to {desc} failed: "
                    f"{type(e).__name__}: {e}")
            finally:
                ticket._done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="mxtpu-ckpt-writer")
        with self._lock:
            self._current = (t, ticket)
        t.start()
        if prev_error is not None:
            prev_error.pending_ticket = ticket
            raise prev_error
        return ticket

    def wait_until_finished(self, timeout=None):
        with self._lock:
            cur = self._current
            self._current = None
        if cur is not None:
            thread, ticket = cur
            try:
                ticket.wait(timeout)
            except CheckpointTimeout:
                # writer still running: keep tracking it so the next
                # save() joins it instead of racing a second writer
                # onto the same .tmp path
                with self._lock:
                    if self._current is None:
                        self._current = cur
                raise
        return True


def _snapshot(arrays):
    """Start a non-blocking D2H for every array; immutability makes this
    a consistent snapshot of "now".  Wraps the captured IMMUTABLE jax
    array, never the caller's mutable handle — later ``w += ...`` on the
    handle must not leak into the snapshot."""
    snap = {}
    for k, v in arrays.items():
        a = v.data if isinstance(v, NDArray) else v
        try:
            a.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        _faults.fault_point("checkpoint.d2h", k)
        if isinstance(a, _np.ndarray):
            a = jax.numpy.asarray(a)
        snap[k] = NDArray(a) if hasattr(a, "dtype") else v
    return snap


_DEFAULT = AsyncCheckpointer()


def save_checkpoint_async(fname, arrays):
    """Module-level convenience over a shared AsyncCheckpointer."""
    return _DEFAULT.save(fname, arrays)


# ---------------------------------------------------------------------------
# CRC helpers (per-array payload bytes, mirroring the nd container format)
# ---------------------------------------------------------------------------

def _payload_bytes(arr):
    """The exact payload bytes ``nd_utils.save`` writes for this array
    (bf16 widens to f32; sparse concatenates its compressed segments) —
    so a CRC computed pre-write can be re-verified from the loaded
    arrays."""
    if not isinstance(arr, NDArray):
        arr = NDArray(jax.numpy.asarray(arr))
    segs = nd_utils._sparse_segments(arr)
    if segs is not None:
        _, _, parts = segs
        return b"".join(_np.ascontiguousarray(p).tobytes() for p in parts)
    return _np.ascontiguousarray(nd_utils._to_numpy_raw(arr)).tobytes()


def _array_crcs(arrays):
    return {k: zlib.crc32(_payload_bytes(v)) for k, v in arrays.items()}


def _file_crc(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


# ---------------------------------------------------------------------------
# RNG state (mx PRNG key + numpy global MT — the streams training draws)
# ---------------------------------------------------------------------------

def _rng_state():
    from .ndarray import random as _rnd
    key_data = _np.asarray(jax.random.key_data(_rnd.current_key()))
    algo, keys, pos, has_gauss, cached = _np.random.get_state()
    arrays = {"mx_key": NDArray(jax.numpy.asarray(key_data)),
              "np_keys": NDArray(jax.numpy.asarray(keys))}
    meta = {"np_algo": algo, "np_pos": int(pos),
            "np_has_gauss": int(has_gauss), "np_cached": float(cached)}
    return arrays, meta


def _restore_rng(arrays, meta):
    from .ndarray import random as _rnd
    _rnd.set_key_data(_np.asarray(arrays["mx_key"].asnumpy(),
                                  dtype=_np.uint32))
    _np.random.set_state((
        meta["np_algo"],
        _np.asarray(arrays["np_keys"].asnumpy(), dtype=_np.uint32),
        int(meta["np_pos"]), int(meta["np_has_gauss"]),
        float(meta["np_cached"])))


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

_MANIFEST = "manifest.json"
_FORMAT_VERSION = 1


def _dp_size():
    """Ambient dp mesh size at save time (recorded in the manifest so a
    resumed run can reshard optimizer state when its dp differs)."""
    from .parallel.mesh import current_mesh, AXIS_DP
    mesh = current_mesh()
    if mesh is not None and AXIS_DP in mesh.axis_names:
        return int(mesh.shape[AXIS_DP])
    return 1


def _mesh_desc():
    """Ambient 3D mesh spec at save time (``"dp8"``, ``"dp2tp2pp2"``,
    ... — ISSUE 11): the manifest records the FULL topology, so a
    restore into any other dp x tp x pp shape knows what it reshards
    from.  The state itself is mesh-independent (per-parameter space);
    this field is provenance, not a restore requirement."""
    from .parallel.mesh import current_mesh, MeshConfig
    mesh = current_mesh()
    if mesh is None:
        return None
    return MeshConfig.for_mesh(mesh).describe()


class CheckpointManager:
    """Atomic full-training-state checkpoints with retention + recovery.

    Usage::

        mgr = CheckpointManager("/ckpts", keep=3)
        step = mgr.latest()
        if step is not None:
            manifest = mgr.restore(step, params=net, trainer=trainer)
            start = manifest["step"]
        ...
        mgr.save(step, params=net, trainer=trainer,
                 iterator={"epoch": e, "batch": b})
        ...
        mgr.wait_until_finished()

    ``params`` may be a gluon ``Block``, a dict of ``Parameter``s, or a
    dict of ``NDArray``s.  ``trainer`` is anything with the
    ``state_dict()`` / ``load_state_dict()`` protocol (``gluon.Trainer``
    and ``parallel.DataParallelTrainer`` both implement it; the latter
    reshards its ZeRO-1 optimizer state when the restored dp size
    differs from the saved one).
    """

    def __init__(self, directory, keep=None, prefix="ckpt",
                 async_save=None):
        self.directory = str(directory)
        self.prefix = prefix
        if keep is None:
            keep = int(os.environ.get("MXTPU_CKPT_KEEP", "3"))
        self.keep = max(1, int(keep))
        if async_save is None:
            async_save = os.environ.get("MXTPU_CKPT_ASYNC", "1") != "0"
        self._async_save = bool(async_save)
        self._writer = AsyncCheckpointer()
        self._timeout = float(os.environ.get("MXTPU_CKPT_TIMEOUT", "0")) \
            or None
        os.makedirs(self.directory, exist_ok=True)

    # -- naming ---------------------------------------------------------
    def _step_dir(self, step):
        return os.path.join(self.directory, f"{self.prefix}-{step:08d}")

    def _scan(self):
        """All on-disk (step, dir) candidates, newest first — validity
        NOT checked here."""
        pat = re.compile(re.escape(self.prefix) + r"-(\d+)$")
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = pat.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort(reverse=True)
        return out

    # -- validation -----------------------------------------------------
    def _validate(self, path):
        """Manifest present + parses, every listed file present with the
        recorded size and CRC32.  Returns the manifest dict or None.
        This is what makes ``latest()`` skip torn (no/partial manifest)
        and corrupt (flipped/truncated payload) checkpoints."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        files = manifest.get("files")
        if not isinstance(files, dict):
            return None
        for fname, rec in files.items():
            fpath = os.path.join(path, fname)
            try:
                if os.path.getsize(fpath) != rec["nbytes"]:
                    return None
                if _file_crc(fpath) != rec["crc32"]:
                    return None
            except (OSError, KeyError, TypeError):
                return None
        return manifest

    def latest(self):
        """Newest step whose checkpoint validates; torn/corrupt
        checkpoints are skipped (older valid ones still restore)."""
        for step, path in self._scan():
            if self._validate(path) is not None:
                return step
        return None

    def steps(self):
        """All valid steps, ascending."""
        return sorted(step for step, path in self._scan()
                      if self._validate(path) is not None)

    def manifest(self, step):
        """The validated manifest for ``step`` (None if torn/corrupt)."""
        return self._validate(self._step_dir(step))

    # -- save -----------------------------------------------------------
    @staticmethod
    def _param_arrays(params):
        if params is None:
            return {}
        if hasattr(params, "_collect_params_with_prefix"):   # gluon Block
            return {name: p.data() for name, p
                    in params._collect_params_with_prefix().items()
                    if p._data is not None}
        out = {}
        for name, v in dict(params).items():
            out[name] = v.data() if hasattr(v, "set_data") else v
        return out

    def save(self, step, params=None, trainer=None, iterator=None,
             extra=None, sync=False):
        """Write checkpoint ``step``.  Device buffers are snapshotted
        before returning; serialization runs on the writer thread unless
        ``sync=True`` (or async saves are disabled).  Returns a ticket
        (``.wait()``) for async saves, the checkpoint path for sync.

        ``iterator`` is either a JSON-able cursor dict (e.g.
        ``{"epoch": 2, "batch": 417}``) or an object with
        ``state_dict()``.  ``extra`` is a JSON-able dict stored verbatim
        in the manifest.
        """
        step = int(step)
        groups = {}
        from . import runtime as _runtime
        meta = {"format": _FORMAT_VERSION, "step": step,
                "time": time.time(), "dp": _dp_size(),
                "mesh": _mesh_desc(),
                # K-step compiled training (ISSUE 6): record the save
                # cadence so a resumed run knows the cursor can only sit
                # on this grid — the cursor itself stays in STEPS, so a
                # resume with a different K (or K=1) fast-forwards to
                # the exact step and re-forms its own windows
                "steps_per_call": _runtime.steps_per_call()}
        p_arrays = self._param_arrays(params)
        if p_arrays:
            groups["params"] = _snapshot(p_arrays)
        if trainer is not None:
            sd = trainer.state_dict()
            groups["trainer"] = _snapshot(sd.get("arrays", {}))
            meta["trainer_meta"] = sd.get("meta", {})
        rng_arrays, rng_meta = _rng_state()
        groups["rng"] = rng_arrays
        meta["rng_meta"] = rng_meta
        if iterator is not None:
            cur = iterator.state_dict() \
                if hasattr(iterator, "state_dict") else dict(iterator)
            meta["iterator"] = cur
        if extra is not None:
            meta["extra"] = dict(extra)

        def write():
            return self._write(step, groups, meta)

        if sync or not self._async_save:
            # surface a previous async failure exactly like save() would
            self._writer.wait_until_finished(self._timeout)
            return write()
        return self._writer._submit(write, desc=self._step_dir(step))

    def _write(self, step, groups, meta):
        t0 = _telem.clock() if _telem.enabled() else None
        path = self._step_dir(step)
        if os.path.isdir(path):
            shutil.rmtree(path)      # overwrite a previous torn attempt
        os.makedirs(path, exist_ok=True)
        files = {}
        array_crc = {}
        for group, arrays in groups.items():
            fname = f"{group}.ndz"
            fpath = os.path.join(path, fname)
            _faults.fault_point("checkpoint.write", fpath)
            # CRCs computed HERE, off the training thread: the snapshot
            # arrays are immutable, so writer-side D2H is still the
            # values of save() time
            array_crc[group] = _array_crcs(arrays)
            nd_utils.save(fpath, arrays)
            files[fname] = {"nbytes": os.path.getsize(fpath),
                            "crc32": _file_crc(fpath)}
        manifest = dict(meta)
        manifest["array_crc"] = array_crc
        manifest["files"] = files
        mpath = os.path.join(path, _MANIFEST)
        tmp = mpath + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        # the commit point: a crash anywhere before this line leaves a
        # manifest-less (torn) directory that latest() skips
        _faults.fault_point("checkpoint.manifest", mpath)
        os.replace(tmp, mpath)
        self._retain(step)
        if t0 is not None:
            # writer-thread side, so the training loop never pays this;
            # bytes = the committed payload files (manifest excluded)
            _telem.observe("checkpoint.save_ms",
                           (_telem.clock() - t0) * 1e3)
            _telem.inc("checkpoint.saves")
            _telem.inc("checkpoint.bytes",
                       sum(f["nbytes"] for f in files.values()))
            _telem.event("checkpoint.saved", step=step)
        return path

    def _retain(self, just_written):
        """Keep the newest ``keep`` valid checkpoints; drop older valid
        ones and any torn leftovers older than the newest valid step."""
        entries = self._scan()
        valid = [(s, p) for s, p in entries
                 if self._validate(p) is not None]
        keep_steps = {s for s, _ in valid[:self.keep]}
        newest_valid = valid[0][0] if valid else just_written
        for step, path in entries:
            if step in keep_steps:
                continue
            if self._validate(path) is None and step >= newest_valid:
                continue       # possibly an in-progress write: leave it
            try:
                shutil.rmtree(path)
            except OSError:
                pass

    def wait_until_finished(self, timeout=None):
        """Join the in-flight write (re-raising its error)."""
        return self._writer.wait_until_finished(
            timeout if timeout is not None else self._timeout)

    # -- restore --------------------------------------------------------
    def _load_group(self, path, manifest, group):
        fname = f"{group}.ndz"
        if fname not in manifest.get("files", {}):
            return {}
        arrays = nd_utils.load(os.path.join(path, fname))
        want = manifest.get("array_crc", {}).get(group, {})
        got = _array_crcs(arrays)
        for name, crc in want.items():
            if got.get(name) != crc:
                raise MXNetError(
                    f"checkpoint {path}: array {group}/{name} CRC "
                    f"mismatch (corrupt payload)")
        return arrays

    def restore(self, step=None, params=None, trainer=None,
                restore_rng=True):
        """Restore checkpoint ``step`` (default: :meth:`latest`).
        Returns the manifest dict (cursor under ``"iterator"``), or
        None when no valid checkpoint exists.

        ``params``: gluon Block (set via structural names) or dict of
        Parameters/NDArrays updated in place.  ``trainer``: restored via
        ``load_state_dict`` — optimizer state is saved dp-independent,
        so a trainer running at a different dp size reshards on load.
        """
        t0 = _telem.clock() if _telem.enabled() else None
        if step is None:
            step = self.latest()
            if step is None:
                return None
        path = self._step_dir(step)
        manifest = self._validate(path)
        if manifest is None:
            raise MXNetError(
                f"checkpoint step {step} at {path} is torn or corrupt")
        if params is not None:
            arrays = self._load_group(path, manifest, "params")
            self._apply_params(params, arrays)
        if trainer is not None:
            arrays = self._load_group(path, manifest, "trainer")
            trainer.load_state_dict(
                {"arrays": arrays,
                 "meta": manifest.get("trainer_meta", {})})
        if restore_rng and "rng.ndz" in manifest.get("files", {}):
            arrays = self._load_group(path, manifest, "rng")
            _restore_rng(arrays, manifest["rng_meta"])
        if t0 is not None:
            _telem.observe("checkpoint.restore_ms",
                           (_telem.clock() - t0) * 1e3)
            _telem.inc("checkpoint.restores")
            _telem.event("checkpoint.restored", step=int(step))
        return manifest

    @staticmethod
    def _apply_params(params, arrays):
        if hasattr(params, "_collect_params_with_prefix"):   # gluon Block
            target = params._collect_params_with_prefix()
            for name, value in arrays.items():
                if name in target:
                    target[name].set_data(value)
                else:
                    raise MXNetError(
                        f"checkpoint parameter {name!r} not present in "
                        f"the target block")
            return
        target = dict(params)
        for name, value in arrays.items():
            if name not in target:
                raise MXNetError(
                    f"checkpoint parameter {name!r} not present in the "
                    f"target dict")
            t = target[name]
            if hasattr(t, "set_data"):
                t.set_data(value)
            elif isinstance(t, NDArray):
                t._set_data(value.data)
            else:
                params[name] = value


# ---------------------------------------------------------------------------
# Elastic reshard-in-place (ISSUE 8) — the state-movement half of a
# membership transition.  The orchestration (when to pause, retries,
# rendezvous, epoch bookkeeping) lives in elastic/controller.py; THIS is
# the one place that knows how to move training state onto a new mesh.
# ---------------------------------------------------------------------------

def reshard_in_place(trainer, mesh, params=None, _attempt=0):
    """Reshard a running trainer to a new ``mesh`` without a process
    restart: capture optimizer state in per-parameter space
    (``state_dict`` — the dp-independent form PR 4 built for cross-dp
    restore) plus a host snapshot of the parameters, rebuild the
    trainer for the new world size (``DataParallelTrainer.rebuild``:
    new BucketPlan, fresh jit caches, re-placed device state), and
    restore both — bitwise the state a fresh process would load from a
    checkpoint of the same instant.

    This is the **peer-to-peer** path: on a real pod the survivors'
    live state is fresher than any checkpoint, so the transfer sources
    from the trainer itself.  The ``elastic.reshard`` fault point fires
    between capture and apply — chaos tests kill the reshard mid-flight
    and the controller falls back to :func:`reshard_from_checkpoint`.

    Returns ``{"source": "peer", "step": None}`` (no rewind: training
    continues at the paused step).

    ``mesh`` may be a ``jax.sharding.Mesh`` or a
    ``parallel.MeshConfig`` (ISSUE 11): an elastic transition re-fences
    all three axes (dp, tp, pp) through ``trainer.rebuild`` — the
    per-parameter state capture below is mesh-shape-independent, so a
    ``2x2x2`` trainer reshards onto ``dp8`` (and back) bitwise.
    """
    if not hasattr(trainer, "rebuild"):
        raise MXNetError(
            f"reshard_in_place needs a trainer with rebuild(mesh) "
            f"(parallel.DataParallelTrainer); got {type(trainer).__name__}")
    state = trainer.state_dict()
    psnap = None
    if params is not None:
        psnap = {name: _np.asarray(p.data().asnumpy())
                 for name, p
                 in params._collect_params_with_prefix().items()
                 if p._data is not None}
    # the kill-during-reshard fault point: armed chaos runs die HERE —
    # after capture, before any mutation — modeling a peer that vanishes
    # mid-transfer; the controller's fallback then restores from disk
    _faults.fault_point("elastic.reshard", int(_attempt))
    trainer.rebuild(mesh)
    if psnap is not None:
        target = params._collect_params_with_prefix()
        for name, v in psnap.items():
            target[name].set_data(v)
    trainer.load_state_dict(state)
    return {"source": "peer", "step": None}


def reshard_from_checkpoint(trainer, mesh, params=None, manager=None):
    """The fallback half of an elastic reshard: the peer transfer
    failed (worker died mid-reshard), so rebuild for the new mesh and
    restore the newest VALID checkpoint (torn/corrupt ones skipped —
    the PR 4 ``latest()`` discipline).  Training must rewind to the
    returned step; the RNG streams are restored with it, so the replay
    is bitwise the original schedule.

    Returns ``{"source": "checkpoint", "step": <restored step>}``.
    """
    if manager is None:
        raise MXNetError(
            "elastic reshard: peer transfer failed and no "
            "CheckpointManager was provided to fall back to")
    if not hasattr(trainer, "rebuild"):
        raise MXNetError(
            f"reshard_from_checkpoint needs a trainer with rebuild(mesh)"
            f"; got {type(trainer).__name__}")
    trainer.rebuild(mesh)
    manifest = manager.restore(params=params, trainer=trainer)
    if manifest is None:
        raise MXNetError(
            "elastic reshard: peer transfer failed and no valid "
            "checkpoint exists — cannot recover without a restart")
    return {"source": "checkpoint", "step": int(manifest["step"]),
            "manifest": manifest}


# ---------------------------------------------------------------------------
# Preemption handling
# ---------------------------------------------------------------------------

class PreemptionHandler:
    """Cooperative SIGTERM/SIGINT handling: the first signal sets a flag
    the training loop checks between steps (finish the in-flight step,
    save, exit cleanly); a second signal raises ``KeyboardInterrupt``
    (the operator really means it).

    Installable as a context manager; signal registration silently
    degrades to flag-only mode off the main thread (fault injection and
    :meth:`request` still work there).
    """

    _current = None          # the installed handler (fault injection)

    def __init__(self, signals=None):
        self.signals = tuple(signals) if signals is not None else \
            (_signal.SIGTERM, _signal.SIGINT)
        self._event = threading.Event()
        self.reason = None
        self._prev = {}
        self._installed_signals = False

    # -- lifecycle ------------------------------------------------------
    def install(self):
        PreemptionHandler._current = self
        try:
            for sig in self.signals:
                self._prev[sig] = _signal.signal(sig, self._on_signal)
            self._installed_signals = True
        except ValueError:       # not the main thread: flag-only mode
            self._prev.clear()
        return self

    def uninstall(self):
        if self._installed_signals:
            for sig, prev in self._prev.items():
                try:
                    _signal.signal(sig, prev)
                except (ValueError, TypeError):
                    pass
            self._prev.clear()
            self._installed_signals = False
        if PreemptionHandler._current is self:
            PreemptionHandler._current = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    @classmethod
    def installed(cls):
        """The currently installed handler (None outside a scope)."""
        return cls._current

    # -- signaling ------------------------------------------------------
    def _on_signal(self, signum, frame):
        if self._event.is_set():
            raise KeyboardInterrupt(
                f"second signal {signum} during preemption drain")
        self.request(reason=f"signal {signum}")

    def request(self, reason="requested"):
        """Flip the preemption flag (signal handler, fault injector, or
        orchestration code).  Also dumps the telemetry flight recorder —
        SIGTERM is exactly the moment the post-mortem must leave the
        process (ISSUE 9); the dump is signal-handler-safe-enough here
        because this runs in the Python-level handler, not the raw C
        one."""
        self.reason = reason
        self._event.set()
        _telem.on_preemption(reason)

    @property
    def requested(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        return self._event.wait(timeout)

    def check_step(self, step):
        """Per-step hook: runs the ``train.step`` fault point (so
        ``inject("train.step", at=K, mode=preempt)`` and the
        ``MXTPU_FAULT_INJECT`` env hook can deliver a simulated
        preemption at step K) and returns whether preemption is
        requested."""
        _faults.fault_point("train.step", int(step))
        return self.requested


def run_preemptible(loop, manager=None, signals=None):
    """Run ``loop(handler)`` under preemption protection.

    Installs a :class:`PreemptionHandler` for the call's duration; the
    loop checks ``handler.requested`` (or ``handler.check_step(step)``)
    between steps, saves its final checkpoint via the manager, and
    returns.  Afterwards the manager's in-flight async write is joined
    so the process never exits with a half-written checkpoint.

    Returns ``(preempted, result)``.
    """
    handler = PreemptionHandler(signals=signals)
    with handler:
        result = loop(handler)
    if manager is not None:
        manager.wait_until_finished()
    return handler.requested, result
