"""Process-level pod runtime: spawn, supervise, commit membership.

Everything "distributed" built through PR 18 — PS heartbeats, elastic
membership, drains, fleet scrapes, the router — ran as threads under
FakeClock in ONE process.  This module is the process-level half of
ISSUE 19: a :class:`PodLauncher` that forks N REAL worker processes
over ``jax.distributed`` (the ``_dist_init`` env seam), supervises
them, and on a real death commits a membership change the survivors
act on by tearing down and re-initializing the JAX coordination
service at the smaller world size (``_dist_init.reinit_distributed``).

Control plane = one directory of atomically-renamed files (the same
medium the checkpoint manager already trusts), so it works with zero
extra sockets and survives any worker death mid-write:

- ``membership.json`` — the committed view ``{epoch, coordinator,
  world, ranks: {orig_rank: new_rank}, dead: [...]}``.  The launcher is
  the ONLY writer; workers poll it at step boundaries.  A new epoch
  carries a FRESH coordinator port: the old coordination service dies
  with the old world (its barrier state is sized to it).
- ``ready.<epoch>.<step>.<orig_rank>`` / ``go.<epoch>.<step>`` — the
  step gate.  Workers report at every step boundary and wait for the
  launcher's approval; the launcher approves a step only while every
  live member is present, so a death observed while workers are PARKED
  at the gate is drained at that boundary (exactly the elastic
  controller's drain-at-step-boundary contract).  The gate cannot
  retract an approval already granted: a kill landing after the go
  file, with survivors inside the step's collective, leaves them
  blocked on the missing peer (the coordination-service heartbeat
  budget is deliberately huge and its callback benign — see
  ``_dist_init``) until ``supervise()``'s pod-level ``timeout_s`` kills
  the pod.  Deterministic mid-step recovery therefore requires the kill
  to land in a parked window — which is what the ``hold_step`` chaos
  hook arranges, and why the chaos scenario kills at a hold.
- ``queue/{pending,inflight,done}`` — the file-lease serving queue.
  Workers claim requests by atomic rename into ``inflight`` (one
  winner per request), write the result into ``done``, then release
  the lease.  On a death the launcher requeues the dead rank's
  unfinished leases back to ``pending`` — completed-but-unreleased
  leases are detected by their ``done`` file and NOT requeued, which
  is what makes the ledger exactly-once.
- ``status.<orig_rank>.json`` / ``digests.<orig_rank>.jsonl`` —
  worker-reported state (pid, epoch, ``jax.process_count()``, step)
  and the per-step parameter digests the chaos gate compares bitwise.

The default worker is ``mxnet_tpu.testing.pod_worker`` (deterministic
dp training over ``process_allgather`` + checkpoint + the queue);
``tools/launch.py --supervise`` drives arbitrary commands through the
same launcher.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["PodLauncher", "read_membership", "write_membership",
           "queue_dirs", "submit_request", "free_port"]

MEMBERSHIP_FILE = "membership.json"


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def write_json_atomic(path, obj):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json(path):
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_membership(pod_dir, epoch, coordinator, ranks, dead=()):
    """Commit a membership view (launcher-only).  ``ranks`` maps
    ORIGINAL rank -> new contiguous rank (0..world-1)."""
    write_json_atomic(os.path.join(pod_dir, MEMBERSHIP_FILE), {
        "epoch": int(epoch), "coordinator": str(coordinator),
        "world": len(ranks),
        "ranks": {str(k): int(v) for k, v in ranks.items()},
        "dead": sorted(int(r) for r in dead)})


def read_membership(pod_dir):
    return read_json(os.path.join(pod_dir, MEMBERSHIP_FILE))


# -- file-lease serving queue ------------------------------------------

def queue_dirs(pod_dir):
    root = os.path.join(pod_dir, "queue")
    dirs = {k: os.path.join(root, k)
            for k in ("pending", "inflight", "done")}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)
    return dirs


def submit_request(pod_dir, req_id, payload):
    dirs = queue_dirs(pod_dir)
    write_json_atomic(os.path.join(dirs["pending"], f"{req_id}.json"),
                      {"id": str(req_id), "payload": payload})


def queue_ledger(pod_dir):
    """{state: [request ids]} — the exactly-once evidence."""
    dirs = queue_dirs(pod_dir)
    out = {}
    for state, d in dirs.items():
        ids = []
        for name in os.listdir(d):
            stem = name.split(".lease.")[0]   # inflight: id.json.lease.R
            if stem.endswith(".json"):
                ids.append(stem[:-5])
        out[state] = sorted(ids)
    return out


class PodLauncher:
    """Spawn + supervise N real worker processes (one pod on one box).

    ``argv`` is the worker command (default: the deterministic
    ``pod_worker``); every worker gets the ``MXTPU_COORDINATOR`` /
    ``MXTPU_PROCESS_ID`` / ``MXTPU_NUM_PROCESSES`` rendezvous env the
    ``_dist_init`` seam consumes, plus ``MXTPU_POD_DIR`` for the
    control plane.  ``supervise()`` runs the gate + death protocol;
    ``kill(rank)`` SIGKILLs a worker (the chaos hook).
    """

    def __init__(self, nprocs, pod_dir, argv=None, env=None,
                 steps=8, ckpt_every=3, devices_per_proc=1):
        self.nprocs = int(nprocs)
        self.pod_dir = os.path.abspath(pod_dir)
        os.makedirs(self.pod_dir, exist_ok=True)
        queue_dirs(self.pod_dir)
        self.argv = list(argv) if argv else [
            sys.executable, "-m", "mxnet_tpu.testing.pod_worker"]
        self.extra_env = dict(env or {})
        self.steps = int(steps)
        self.ckpt_every = int(ckpt_every)
        self.devices_per_proc = int(devices_per_proc)
        self.epoch = 0
        self.coordinator = None
        self.procs = {}          # orig_rank -> Popen (live or reaped)
        self.dead = set()        # orig ranks declared dead
        self.done = set()        # orig ranks that exited clean (rc 0)
        self.ps_ports = {r: free_port() for r in range(self.nprocs)}
        self.reinit_events = []  # [{epoch, world, dead}] per commit
        # chaos hook: while set, the gate withholds approval for steps
        # >= hold_step — every live worker parks at the gate (between
        # collectives), giving a deterministic SIGKILL window
        self.hold_step = None

    # -- membership ----------------------------------------------------
    def _live(self):
        return [r for r in self.procs
                if r not in self.dead and r not in self.done]

    def _commit(self):
        """Commit the current live set as a new epoch with a fresh
        coordinator. Survivors re-rank contiguously in orig-rank order
        (deterministic, so the resumed run is bitwise reproducible)."""
        self.epoch += 1
        self.coordinator = f"127.0.0.1:{free_port()}"
        live = sorted(self._live()) or list(range(self.nprocs))
        ranks = {orig: new for new, orig in enumerate(live)}
        write_membership(self.pod_dir, self.epoch, self.coordinator,
                         ranks, dead=self.dead)
        self.reinit_events.append({"epoch": self.epoch,
                                   "world": len(ranks),
                                   "dead": sorted(self.dead)})
        return ranks

    # -- spawn ----------------------------------------------------------
    def _worker_env(self, orig_rank, new_rank, world):
        env = dict(os.environ)
        env.update(self.extra_env)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        env.update({
            "MXTPU_COORDINATOR": self.coordinator,
            "MXTPU_NUM_PROCESSES": str(world),
            "MXTPU_PROCESS_ID": str(new_rank),
            "MXTPU_POD_DIR": self.pod_dir,
            "MXTPU_POD_RANK": str(orig_rank),
            "MXTPU_POD_EPOCH": str(self.epoch),
            "MXTPU_POD_STEPS": str(self.steps),
            "MXTPU_POD_CKPT_EVERY": str(self.ckpt_every),
            "MXTPU_POD_PS_PORT": str(self.ps_ports[orig_rank]),
            "JAX_PLATFORMS": "cpu",
            # the parent test/bench process often forces 8 virtual CPU
            # devices; a pod worker is ONE host with its own devices
            "XLA_FLAGS": "--xla_force_host_platform_device_count="
                         f"{self.devices_per_proc}",
        })
        return env

    def start(self):
        ranks = self._commit()     # epoch 1: everyone, identity ranks
        for orig, new in ranks.items():
            self.procs[orig] = subprocess.Popen(
                self.argv, env=self._worker_env(orig, new, len(ranks)),
                cwd=self.pod_dir)
        return self

    # -- chaos hook ------------------------------------------------------
    def kill(self, orig_rank, sig=signal.SIGKILL):
        p = self.procs[orig_rank]
        if p.poll() is None:
            p.send_signal(sig)
            p.wait()

    # -- the gate + death protocol --------------------------------------
    def _requeue_leases(self, dead_ranks):
        """Return a dead rank's unfinished leases to ``pending``; a
        lease whose result already landed in ``done`` is completed
        work — release it instead of requeueing (exactly-once)."""
        dirs = queue_dirs(self.pod_dir)
        requeued = []
        for name in os.listdir(dirs["inflight"]):
            stem, _, owner = name.rpartition(".lease.")
            # a name without a numeric owner suffix is not a lease we
            # wrote — skip it rather than crashing supervise() mid
            # death-handling over one corrupt/foreign file
            if not stem or not owner.isdigit() \
                    or int(owner) not in dead_ranks:
                continue
            src = os.path.join(dirs["inflight"], name)
            if os.path.exists(os.path.join(dirs["done"], stem)):
                os.unlink(src)
                continue
            os.replace(src, os.path.join(dirs["pending"], stem))
            requeued.append(stem.rsplit(".json", 1)[0])
        return requeued

    def _reap(self):
        """Newly-dead orig ranks (unexpected exit).  rc==0 is a clean
        completion, not a death."""
        newly = []
        for r, p in self.procs.items():
            if r in self.dead or r in self.done:
                continue
            rc = p.poll()
            if rc is None:
                continue
            if rc == 0:
                self.done.add(r)
            else:
                newly.append(r)
        return newly

    def _gate_scan(self):
        """Approve any step for which EVERY live member has reported
        ready at the current epoch."""
        live = self._live()
        if not live:
            return
        counts = {}
        for name in os.listdir(self.pod_dir):
            if not name.startswith(f"ready.{self.epoch}."):
                continue
            _, _, step, rank = name.split(".")
            if int(rank) in self.dead:
                continue
            counts.setdefault(int(step), set()).add(int(rank))
        for step, ranks in sorted(counts.items()):
            if self.hold_step is not None and step >= self.hold_step:
                continue
            go = os.path.join(self.pod_dir, f"go.{self.epoch}.{step}")
            if ranks >= set(live) and not os.path.exists(go):
                write_json_atomic(go, {"step": step})

    def ready_ranks(self, step, epoch=None):
        """Orig ranks currently parked at the gate for ``step``."""
        epoch = self.epoch if epoch is None else epoch
        out = set()
        prefix = f"ready.{epoch}.{step}."
        for name in os.listdir(self.pod_dir):
            if name.startswith(prefix):
                out.add(int(name[len(prefix):]))
        return out

    def supervise(self, poll_s=0.02, timeout_s=120.0, on_death=None):
        """Run the pod to completion: drive the step gate, and on a
        death requeue its leases and commit a shrunk membership (the
        survivors reinit + restore at the next gate poll).  Returns a
        summary dict.  ``on_death(orig_rank, epoch)`` is the chaos
        observation hook.

        Recovery is deterministic only for deaths drained at a gate
        (survivors parked, e.g. under ``hold_step``).  A kill landing
        mid-step can leave survivors blocked inside a collective on the
        missing peer; nothing interrupts that (see the module
        docstring), so the only backstop is ``timeout_s``: the whole
        pod is killed and a :class:`TimeoutError` raised."""
        deadline = time.monotonic() + timeout_s
        requeued = []
        while self._live():
            if time.monotonic() > deadline:
                for r in self._live():
                    self.kill(r, signal.SIGKILL)
                raise TimeoutError(
                    f"pod did not finish within {timeout_s}s "
                    f"(live={self._live()})")
            newly = self._reap()
            if newly:
                self.dead.update(newly)
                requeued += self._requeue_leases(set(newly))
                self._commit()
                for r in newly:
                    if on_death is not None:
                        on_death(r, self.epoch)
            self._gate_scan()
            time.sleep(poll_s)
        return {"epoch": self.epoch, "dead": sorted(self.dead),
                "done": sorted(self.done), "requeued": requeued,
                "reinits": list(self.reinit_events)}

    def shutdown(self):
        for r, p in self.procs.items():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        t0 = time.monotonic()
        for p in self.procs.values():
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, 5 - (time.monotonic() - t0)))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    # -- evidence --------------------------------------------------------
    def statuses(self):
        out = {}
        for r in range(self.nprocs):
            st = read_json(os.path.join(self.pod_dir,
                                        f"status.{r}.json"))
            if st is not None:
                out[r] = st
        return out

    def digests(self, orig_rank):
        path = os.path.join(self.pod_dir, f"digests.{orig_rank}.jsonl")
        rows = []
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        except OSError:
            pass
        return rows
