"""``mx.metric`` — evaluation metrics.

Reference: python/mxnet/metric.py (EvalMetric base: update(labels, preds) /
get(); Accuracy, TopKAccuracy, F1, MCC, MAE, MSE, RMSE, CrossEntropy,
Perplexity, PearsonCorrelation, Loss, Custom, CompositeEvalMetric).
Accumulation happens in numpy on host — metrics are the sync point of the
training loop anyway (SURVEY.md §3.2).
"""
from __future__ import annotations

import math

import numpy as _np

from .base import MXNetError, registry_create
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy", "Perplexity",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss",
           "CustomMetric", "np", "create", "register"]

register, _create_registered, _REGISTRY = registry_create("metric")


# short names the reference accepts (python/mxnet/metric.py aliases)
_ALIASES = {"acc": "accuracy", "ce": "crossentropy",
            "top_k_acc": "topkaccuracy", "top_k_accuracy": "topkaccuracy",
            "nll_loss": "negativeloglikelihood"}


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        metric = _ALIASES.get(metric.lower(), metric)
    return _create_registered(metric, *args, **kwargs)


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if not isinstance(labels, (list, tuple)):
        labels = [labels]
    if not isinstance(preds, (list, tuple)):
        preds = [preds]
    if len(labels) != len(preds):
        raise MXNetError(f"Shape of labels {len(labels)} does not match "
                         f"shape of predictions {len(preds)}")
    return labels, preds


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name)
            values.append(value)
        return names, values


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if pred.ndim > label.ndim:
                pred = _np.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").flatten()
            label = label.astype("int32").flatten()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy if top_k is 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype("int32")
            pred = _as_np(pred)
            assert pred.ndim == 2, "Predictions should be 2 dims"
            pred_idx = _np.argsort(pred, axis=1)[:, ::-1][:, :self.top_k]
            self.sum_metric += float(
                (pred_idx == label.reshape(-1, 1)).any(axis=1).sum())
            self.num_inst += label.shape[0]


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).flatten().astype("int32")
            pred = _as_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = _np.argmax(pred, axis=-1)
            else:
                pred = (pred.flatten() > 0.5).astype("int32")
            pred = pred.flatten().astype("int32")
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self._stats = _np.zeros(4)  # tp fp tn fn

    def reset(self):
        super().reset()
        self._stats = _np.zeros(4)

    def update(self, labels, preds):
        for label, pred in zip(*check_label_shapes(labels, preds)):
            label = _as_np(label).flatten().astype("int32")
            pred = _as_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = _np.argmax(pred, -1)
            pred = pred.flatten().astype("int32")
            self._stats += [((pred == 1) & (label == 1)).sum(),
                            ((pred == 1) & (label == 0)).sum(),
                            ((pred == 0) & (label == 0)).sum(),
                            ((pred == 0) & (label == 1)).sum()]
            tp, fp, tn, fn = self._stats
            denom = math.sqrt(max((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn),
                                  1e-12))
            self.sum_metric = (tp * tn - fp * fn) / denom
            self.num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(*check_label_shapes(labels, preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(*check_label_shapes(labels, preds)):
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(*check_label_shapes(labels, preds)):
            label = _as_np(label).ravel().astype("int32")
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    """Reference metric.NegativeLogLikelihood: same accumulation as
    CrossEntropy under its canonical name/alias."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(*check_label_shapes(labels, preds)):
            label = _as_np(label).ravel().astype("int32")
            pred = _as_np(pred).reshape(-1, _as_np(pred).shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                prob = prob[~ignore]
                num += (~ignore).sum()
            else:
                num += label.shape[0]
            loss += float(-_np.log(_np.maximum(prob, 1e-12)).sum())
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(*check_label_shapes(labels, preds)):
            label = _as_np(label).ravel()
            pred = _as_np(pred).ravel()
            self.sum_metric += float(_np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Dummy metric for directly printing loss values."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = _as_np(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        name = name if name is not None else getattr(feval, "__name__",
                                                     "custom")
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a CustomMetric (reference mx.metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = getattr(numpy_feval, "__name__", "feval")
    return CustomMetric(feval, name, allow_extra_outputs)
