"""``mx.profiler`` — profiling API over jax.profiler.

Reference: python/mxnet/profiler.py + src/profiler/ (SURVEY.md §5.1). The
reference wrote Chrome-trace JSON from a C++ ring buffer; here
``jax.profiler`` produces TensorBoard/perfetto traces of the actual XLA
execution, exposed behind the same set_config/start/stop/dumps API, plus the
custom Task/Frame/Counter/Marker objects for user annotation.
"""
from __future__ import annotations

import json
import os
import time
import warnings

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "reset", "Task", "Frame", "Counter", "Marker",
           "Domain", "scope", "record_span"]

_CONFIG = {"filename": "profile.json", "profile_all": False,
           "aggregate_stats": False}
_STATE = {"running": False, "trace_dir": None, "events": [],
          "t0": None}


def set_config(**kwargs):
    """Accepts the reference kwargs (profile_all, profile_symbolic,
    profile_imperative, profile_memory, profile_api, aggregate_stats,
    filename, ...)."""
    _CONFIG.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start(profile_process="worker"):
    import jax
    trace_dir = os.path.splitext(_CONFIG.get("filename",
                                             "profile.json"))[0] + "_trace"
    try:
        jax.profiler.start_trace(trace_dir)
        _STATE["trace_dir"] = trace_dir
    except Exception as e:  # already running etc.
        warnings.warn(f"jax trace not started: {e}")
    _STATE["running"] = True
    _STATE["t0"] = time.time()


def stop(profile_process="worker"):
    import jax
    if _STATE.get("trace_dir"):
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _STATE["trace_dir"] = None
    _STATE["running"] = False


def pause(profile_process="worker"):
    stop()


def resume(profile_process="worker"):
    start()


def dump(finished=True, profile_process="worker"):
    """Write collected custom events as Chrome trace JSON (the reference
    format), alongside the XLA trace directory."""
    events = [{"name": name, "ph": ph, "ts": ts * 1e6, "pid": 0, "tid": 0,
               **extra}
              for name, ph, ts, extra in _STATE["events"]]
    with open(_CONFIG["filename"], "w") as f:
        json.dump({"traceEvents": events}, f)


def dumps(reset=False):
    """Aggregate table of scoped events (reference: aggregate_stats.cc /
    mx.profiler.dumps): per-name count and total duration."""
    opens = {}
    stats = {}
    for name, ph, ts, _ in _STATE["events"]:
        if ph == "B":
            opens.setdefault(name, []).append(ts)
        elif ph == "E" and opens.get(name):
            t0 = opens[name].pop()
            cnt, tot = stats.get(name, (0, 0.0))
            stats[name] = (cnt + 1, tot + (ts - t0))
    lines = ["Profile Statistics:",
             f"{'Name':<32}{'Count':>8}{'Total(ms)':>12}"]
    for name, (cnt, tot) in sorted(stats.items()):
        lines.append(f"{name:<32}{cnt:>8}{tot * 1e3:>12.3f}")
    lines.append(f"(XLA trace under "
                 f"{os.path.splitext(_CONFIG['filename'])[0]}_trace)")
    if reset:
        _STATE["events"] = []
    return "\n".join(lines)


def reset():
    """Drop every collected custom event (the ``dumps()`` aggregation
    source).  The span store is process-global, so without this seam two
    tests' B/E events could pair ACROSS tests and span assertions would
    flake depending on test order — the exact failure mode the gluon
    name-counter fixture fixed for auto-naming (PR 5).  A conftest
    autouse hook calls this around every test."""
    _STATE["events"] = []


def _span_context():
    """The ambient {step, epoch} tags (mx.telemetry context) attached to
    every span while a profile runs, so perfetto/Chrome-trace rows
    correlate with the telemetry event log (ISSUE 9)."""
    from . import telemetry as _telem
    ctx = _telem.context()
    return {"args": ctx} if ctx else {}


def _emit(name, ph, **extra):
    if not extra:
        extra = _span_context()
    _STATE["events"].append((name, ph, time.time(), extra))


def record_span(name, t0, t1):
    """Record an already-completed [t0, t1] span (perf_counter or epoch
    seconds) when a profile is running; no-op otherwise.

    Used by the input-pipeline stages (``io.DevicePrefetcher`` /
    ``io.AsyncDecodeIter`` worker threads) so decode/H2D/stall show up
    in ``dumps()`` next to the step — list.append is atomic under the
    GIL, so cross-thread emission needs no lock.  Spans are tagged with
    the current telemetry step/epoch for trace correlation."""
    if not _STATE["running"]:
        return
    extra = _span_context()
    _STATE["events"].append((name, "B", t0, extra))
    _STATE["events"].append((name, "E", t1, extra))


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Scoped:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def start(self):
        _emit(self.name, "B")

    def stop(self):
        _emit(self.name, "E")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_Scoped):
    pass


class Frame(_Scoped):
    pass


class Counter:
    def __init__(self, domain, name, value=None):
        self.name = name
        self.value = value or 0

    def set_value(self, value):
        self.value = value
        _emit(self.name, "C", args={"value": value})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


class Marker:
    def __init__(self, domain, name):
        self.name = name

    def mark(self, scope="process"):
        _emit(self.name, "i", s=scope[0])


class scope:
    """Annotate a region; inside jit this becomes a jax.named_scope so the
    region is visible in the XLA trace."""

    def __init__(self, name):
        self.name = name
        self._ctx = None

    def __enter__(self):
        import jax
        self._ctx = jax.named_scope(self.name)
        self._ctx.__enter__()
        _emit(self.name, "B")
        return self

    def __exit__(self, *exc):
        _emit(self.name, "E")
        return self._ctx.__exit__(*exc)
