"""``mx.optimizer`` package (reference: python/mxnet/optimizer/)."""
from .optimizer import *  # noqa: F401,F403
from .optimizer import register, create, Optimizer, Updater, get_updater
from . import lr_scheduler
