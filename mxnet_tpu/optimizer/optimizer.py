"""``mx.optimizer`` — optimizers with MXNet's create_state/update contract.

Reference: python/mxnet/optimizer/optimizer.py + the fused update kernels in
src/operator/optimizer_op.cc (SURVEY.md §2.2 "Optimizers"). Each ``update``
here is a single fused jax function per parameter (XLA fuses the elementwise
chain — the role of the reference's hand-fused CUDA kernels); Trainer's
hybridized path goes further and folds ALL parameter updates into the one
jitted train step.

Covers: SGD(+momentum), NAG, Adam, AdamW, AdaGrad, AdaDelta, RMSProp, Ftrl,
Signum, LAMB, LARS, SGLD, DCASGD, MultiSGD-equivalent fused group update.
"""
from __future__ import annotations

import math

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError, registry_create
from ..ndarray.ndarray import NDArray
from ..ndarray import random as _rnd

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "AdaGrad", "AdaDelta",
           "RMSProp", "Ftrl", "Signum", "LAMB", "LARS", "SGLD", "Test",
           "register", "create", "Updater", "get_updater", "fused_rule"]

register, create, _REGISTRY = registry_create("optimizer")


# ---------------------------------------------------------------------------
# Pure functional update kernels — the SINGLE source of update math
# (VERDICT r1 #6). The eager Optimizer.update methods below delegate to
# these, and parallel.DataParallelTrainer jits them directly, so the fused
# and eager paths can never diverge. Each kernel is
#   init(p)                  -> state dict of arrays
#   apply(p, g, s, lr, wd)   -> (new_p, new_state)
# with g already rescaled+clipped by the caller; wd semantics (coupled vs
# decoupled) live inside the kernel. Reference: the fused CUDA update
# kernels in src/operator/optimizer_op.cc collapse to these jnp chains
# (XLA fuses the elementwise ops; one kernel launch per parameter).
# ---------------------------------------------------------------------------

def _k_sgd(momentum=0.0, nesterov=False, lazy_update=None):
    def init(p):
        return {"mom": jnp.zeros_like(p)} if momentum else {}

    def apply(p, g, s, lr, wd):
        g = g + wd * p
        if not momentum:
            return p - lr * g, dict(s)
        if nesterov:
            m = momentum * s["mom"] + g
            return p - lr * (g + momentum * m), {"mom": m}
        m = momentum * s["mom"] - lr * g
        return p + m, {"mom": m}
    return init, apply


def _k_adam(beta1=0.9, beta2=0.999, epsilon=1e-8, decoupled_wd=False,
            lazy_update=None):
    def init(p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "t": jnp.zeros((), jnp.int32)}

    def apply(p, g, s, lr, wd):
        if not decoupled_wd:
            g = g + wd * p
        t = s["t"] + 1
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        m = beta1 * s["m"] + (1 - beta1) * g
        v = beta2 * s["v"] + (1 - beta2) * jnp.square(g)
        lr_t = lr * jnp.sqrt(1 - beta2 ** tf) / (1 - beta1 ** tf)
        new_p = p - lr_t * m / (jnp.sqrt(v) + epsilon)
        if decoupled_wd:
            new_p = new_p - lr * wd * p
        return new_p, {"m": m, "v": v, "t": t}
    return init, apply


def _k_lamb(beta1=0.9, beta2=0.999, epsilon=1e-6, lower_bound=None,
            upper_bound=None, bias_correction=True):
    def init(p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "t": jnp.zeros((), jnp.int32)}

    def apply(p, g, s, lr, wd):
        t = s["t"] + 1
        tf = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        m = beta1 * s["m"] + (1 - beta1) * g
        v = beta2 * s["v"] + (1 - beta2) * jnp.square(g)
        if bias_correction:
            m_hat = m / (1 - beta1 ** tf)
            v_hat = v / (1 - beta2 ** tf)
        else:
            m_hat, v_hat = m, v
        update = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * p
        w_norm = jnp.linalg.norm(p)
        u_norm = jnp.linalg.norm(update)
        if lower_bound is not None:
            w_norm = jnp.maximum(w_norm, lower_bound)
        if upper_bound is not None:
            w_norm = jnp.minimum(w_norm, upper_bound)
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p - lr * ratio * update, {"m": m, "v": v, "t": t}
    return init, apply


def _k_lars(eta=0.001, eps=1e-8, momentum=0.0):
    def init(p):
        return {"mom": jnp.zeros_like(p)} if momentum else {}

    def apply(p, g, s, lr, wd):
        w_norm = jnp.linalg.norm(p)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where((w_norm > 0) & (g_norm > 0),
                          eta * w_norm / (g_norm + wd * w_norm + eps), 1.0)
        g = (g + wd * p) * trust
        if momentum:
            m = momentum * s["mom"] - lr * g
            return p + m, {"mom": m}
        return p - lr * g, dict(s)
    return init, apply


def _k_rmsprop(gamma1=0.9, gamma2=0.9, epsilon=1e-8, centered=False,
               clip_weights=None):
    def init(p):
        if centered:
            return {"n": jnp.zeros_like(p), "g": jnp.zeros_like(p),
                    "d": jnp.zeros_like(p)}
        return {"n": jnp.zeros_like(p)}

    def apply(p, g, s, lr, wd):
        g = g + wd * p
        if not centered:
            n = (1 - gamma1) * jnp.square(g) + gamma1 * s["n"]
            w = p - lr * g / jnp.sqrt(n + epsilon)
            new_s = {"n": n}
        else:
            n = (1 - gamma1) * jnp.square(g) + gamma1 * s["n"]
            gbar = (1 - gamma1) * g + gamma1 * s["g"]
            d = gamma2 * s["d"] - lr * g / jnp.sqrt(
                n - jnp.square(gbar) + epsilon)
            w = p + d
            new_s = {"n": n, "g": gbar, "d": d}
        if clip_weights:
            w = jnp.clip(w, -clip_weights, clip_weights)
        return w, new_s
    return init, apply


_FUSED_KERNELS = {
    "sgd": _k_sgd,
    "nag": lambda **kw: _k_sgd(nesterov=True, **kw),
    "adam": _k_adam,
    "adamw": lambda **kw: _k_adam(decoupled_wd=True, **kw),
    "lamb": _k_lamb,
    "lars": _k_lars,
    "rmsprop": _k_rmsprop,
}


def fused_rule(name, clip_gradient=None, **hyper):
    """Return ``(init, apply)`` pure update kernels for optimizer ``name``.

    ``apply(p, g, state, lr, wd)`` — jit/vmap/shard_map-safe; used by
    ``parallel.DataParallelTrainer`` to fold every parameter update into the
    one compiled train step. Raises for optimizers without a functional
    kernel (use the eager ``gluon.Trainer`` path for those).
    """
    factory = _FUSED_KERNELS.get(name.lower() if isinstance(name, str)
                                 else name)
    if factory is None:
        raise MXNetError(
            f"no fused kernel for optimizer '{name}'; supported: "
            f"{sorted(_FUSED_KERNELS)}")
    init, kernel = factory(**hyper)

    def apply(p, g, s, lr, wd=0.0):
        if clip_gradient is not None:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return kernel(p, g, s, lr, wd)
    return init, apply


class Optimizer:
    """Base optimizer. Reference contract: create_state(index, weight) ->
    state; update(index, weight, grad, state) mutates weight in place."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self.multi_precision = multi_precision
        self._index_update_count = {}
        self.idx2name = param_idx2name.copy() if param_idx2name else {}
        self.param_dict = param_dict if param_dict else {}
        self.lr_mult = {}
        self.wd_mult = {}

    create = staticmethod(lambda name, **kwargs: create(name, **kwargs))

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy = NDArray(weight.data.astype(jnp.float32),
                                         weight.context)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            inner_state, master = state
            grad32 = NDArray(grad.data.astype(jnp.float32), grad.context)
            self.update(index, master, grad32, inner_state)
            weight._set_data(master.data.astype(jnp.float16))
        else:
            self.update(index, weight, grad, state)

    # -- bookkeeping -------------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot set lr directly")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _rescale_clip(self, g):
        """Common grad preprocessing: rescale then clip (wd is applied by
        the caller or inside the functional kernel)."""
        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _preprocess_grad(self, g, w, wd):
        g = self._rescale_clip(g)
        if wd:
            g = g + wd * w
        return g


@register
class SGD(Optimizer):
    """SGD with momentum. Reference: optimizer.SGD + sgd_mom_update kernel
    (src/operator/optimizer_op.cc). Lazy sparse updates are accepted and
    executed densely (XLA has no sparse apply)."""

    _nesterov = False

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                       weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray) and self.lazy_update and \
                self.momentum == 0.0 and not self._nesterov:
            # lazy row-sparse update: touch only the nnz rows (reference
            # sgd_update kRowSparseStorage path) — O(nnz) not O(vocab)
            rows = grad.indices.data
            g = self._rescale_clip(grad.values.data)
            w = weight.data
            if wd:
                g = g + wd * jnp.take(w, rows, axis=0)
            weight._set_data(w.at[rows].add(-lr * g))
            return
        g = self._rescale_clip(grad.data)
        _, apply = _k_sgd(momentum=self.momentum, nesterov=self._nesterov)
        s = {"mom": state.data} if state is not None else {}
        new_w, new_s = apply(weight.data, g, s, lr, wd)
        if state is not None:
            state._set_data(new_s["mom"])
        weight._set_data(new_w)


@register
class NAG(SGD):
    """Nesterov accelerated SGD. Reference: optimizer.NAG."""

    _nesterov = True


@register
class Adam(Optimizer):
    """Reference: optimizer.Adam + adam_update kernel. Bias correction folded
    into the step size exactly as the reference does."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                            weight.context)
        return (z(), z())  # mean, var

    _decoupled_wd = False

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray) and \
                getattr(self, "lazy_update", True) and \
                not self._decoupled_wd:
            # lazy adam (reference adam_update kRowSparseStorage): moments
            # and weight touched only at nnz rows
            rows = grad.indices.data
            g = self._rescale_clip(grad.values.data)
            w = weight.data
            if wd:
                g = g + wd * jnp.take(w, rows, axis=0)
            m_r = self.beta1 * jnp.take(mean.data, rows, axis=0) + \
                (1 - self.beta1) * g
            v_r = self.beta2 * jnp.take(var.data, rows, axis=0) + \
                (1 - self.beta2) * jnp.square(g)
            lr_t = lr * math.sqrt(1 - self.beta2 ** t) / \
                (1 - self.beta1 ** t)
            mean._set_data(mean.data.at[rows].set(m_r))
            var._set_data(var.data.at[rows].set(v_r))
            weight._set_data(w.at[rows].add(
                -lr_t * m_r / (jnp.sqrt(v_r) + self.epsilon)))
            return
        g = self._rescale_clip(grad.data)
        _, apply = _k_adam(beta1=self.beta1, beta2=self.beta2,
                           epsilon=self.epsilon,
                           decoupled_wd=self._decoupled_wd)
        s = {"m": mean.data, "v": var.data, "t": t - 1}
        new_w, new_s = apply(weight.data, g, s, lr, wd)
        mean._set_data(new_s["m"])
        var._set_data(new_s["v"])
        weight._set_data(new_w)


@register
class AdamW(Adam):
    """Decoupled weight decay (reference: contrib adamw_update op)."""

    _decoupled_wd = True


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                       weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess_grad(grad.data, weight.data, wd)
        h = state.data + jnp.square(g)
        state._set_data(h)
        weight._set_data(weight.data - lr * g /
                         (jnp.sqrt(h) + self.float_stable_eps))


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                            weight.context)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        g = self._preprocess_grad(grad.data, weight.data, wd)
        ag = self.rho * acc_g.data + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta.data + self.epsilon) / \
            jnp.sqrt(ag + self.epsilon) * g
        ad = self.rho * acc_delta.data + (1 - self.rho) * jnp.square(delta)
        acc_g._set_data(ag)
        acc_delta._set_data(ad)
        weight._set_data(weight.data - delta)


@register
class RMSProp(Optimizer):
    """Reference: optimizer.RMSProp (centered=False default, gamma1/gamma2)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                            weight.context)
        if self.centered:
            return (z(), z(), z())  # n, g, delta
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._rescale_clip(grad.data)
        _, apply = _k_rmsprop(gamma1=self.gamma1, gamma2=self.gamma2,
                              epsilon=self.epsilon, centered=self.centered,
                              clip_weights=self.clip_weights)
        if self.centered:
            n, gbar, delta = state
            s = {"n": n.data, "g": gbar.data, "d": delta.data}
        else:
            (n,) = state
            s = {"n": n.data}
        new_w, new_s = apply(weight.data, g, s, lr, wd)
        n._set_data(new_s["n"])
        if self.centered:
            gbar._set_data(new_s["g"])
            delta._set_data(new_s["d"])
        weight._set_data(new_w)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                            weight.context)
        return (z(), z())  # z, n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        zs, ns = state
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        n_new = ns.data + jnp.square(g)
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(ns.data)) / lr
        z_new = zs.data + g - sigma * weight.data
        ns._set_data(n_new)
        zs._set_data(z_new)
        w = -(z_new - jnp.sign(z_new) * self.lamda1) / \
            ((self.beta + jnp.sqrt(n_new)) / lr + wd)
        weight._set_data(jnp.where(jnp.abs(z_new) <= self.lamda1,
                                   jnp.zeros_like(w), w))


@register
class Signum(Optimizer):
    """Reference: optimizer.Signum (signSGD + momentum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                       weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess_grad(grad.data, weight.data, wd)
        if state is not None:
            m = self.momentum * state.data - (1 - self.momentum) * g
            state._set_data(m)
            step = jnp.sign(m)
        else:
            step = -jnp.sign(g)
        weight._set_data((1 - lr * self.wd_lh) * weight.data + lr * step)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments for large-batch BERT (reference [≥1.6]:
    optimizer.LAMB / lamb_update ops)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                            weight.context)
        return (z(), z())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        g = self._rescale_clip(grad.data)
        _, apply = _k_lamb(beta1=self.beta1, beta2=self.beta2,
                           epsilon=self.epsilon,
                           lower_bound=self.lower_bound,
                           upper_bound=self.upper_bound,
                           bias_correction=self.bias_correction)
        s = {"m": mean.data, "v": var.data, "t": t - 1}
        new_w, new_s = apply(weight.data, g, s, lr, wd)
        mean._set_data(new_s["m"])
        var._set_data(new_s["v"])
        weight._set_data(new_w)


@register
class LARS(SGD):
    """Layer-wise adaptive rate scaling for large-batch CNNs (reference
    [≥1.6]: optimizer.LARS)."""

    def __init__(self, eta=0.001, eps=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.eta = eta
        self.eps = eps

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._rescale_clip(grad.data)
        _, apply = _k_lars(eta=self.eta, eps=self.eps,
                           momentum=self.momentum)
        s = {"mom": state.data} if state is not None else {}
        new_w, new_s = apply(weight.data, g, s, lr, wd)
        if state is not None:
            state._set_data(new_s["mom"])
        weight._set_data(new_w)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = self._preprocess_grad(grad.data, weight.data, wd)
        noise = jax.random.normal(_rnd.next_key(), weight.shape,
                                  weight.data.dtype) * math.sqrt(lr)
        weight._set_data(weight.data - lr / 2 * g + noise)


@register
class Test(Optimizer):
    """Reference optimizer.Test — simple SGD used by test_optimizer."""

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype),
                       weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data(weight.data - self.lr * grad.data * self.rescale_grad)


ccSGD = SGD


class Updater:
    """KVStore server-side updater (reference optimizer.get_updater /
    kvstore set_optimizer path)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle
        serial = {}
        for k, s in self.states.items():
            serial[k] = _serialize_state(s)
        return pickle.dumps((serial, None))

    def set_states(self, states):
        import pickle
        serial, _ = pickle.loads(states)
        from ..ndarray.ndarray import array as _array
        self.states = {k: _deserialize_state(v) for k, v in serial.items()}


def _serialize_state(s):
    if s is None:
        return None
    if isinstance(s, NDArray):
        return ("nd", s.asnumpy())
    if isinstance(s, tuple):
        return ("tuple", tuple(_serialize_state(x) for x in s))
    return ("raw", s)


def _deserialize_state(v):
    from ..ndarray.ndarray import array as _array
    if v is None:
        return None
    tag, payload = v
    if tag == "nd":
        return _array(payload, dtype=str(payload.dtype))
    if tag == "tuple":
        return tuple(_deserialize_state(x) for x in payload)
    return payload


def get_updater(optimizer):
    return Updater(optimizer)
