"""``mx.lr_scheduler`` — learning-rate schedules with warmup.

Reference: python/mxnet/lr_scheduler.py (Factor/MultiFactor/Poly/Cosine with
warmup_steps/warmup_begin_lr/warmup_mode).
"""
from __future__ import annotations

import math

from ..base import MXNetError

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        if warmup_mode not in ("linear", "constant"):
            raise MXNetError("warmup_mode must be linear or constant")
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        assert num_update < self.warmup_steps
        if self.warmup_mode != "linear":
            return self.warmup_begin_lr
        # linear ramp: begin_lr -> final_lr over warmup_steps updates
        frac = num_update / self.warmup_steps
        return (1.0 - frac) * self.warmup_begin_lr + \
            frac * self.warmup_final_lr

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    def __init__(self, step, factor=1, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if step < 1:
            raise MXNetError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise MXNetError("Factor must be no more than 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step, factor=1, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        assert isinstance(step, list) and len(step) >= 1
        for i, _step in enumerate(step):
            if i != 0 and step[i] <= step[i - 1]:
                raise MXNetError("Schedule step must be an increasing list")
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
            else:
                return self.base_lr
        return self.base_lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        assert isinstance(max_update, int)
        if max_update < 1:
            raise MXNetError("maximum number of updates must be strictly positive")
        self.power = pwr
        self.base_lr_orig = self.base_lr
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = self.max_update - self.warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update <= self.max_update:
            self.base_lr = self.final_lr + \
                (self.base_lr_orig - self.final_lr) * \
                pow(1 - float(num_update - self.warmup_steps) / float(self.max_steps),
                    self.power)
        return self.base_lr


class CosineScheduler(LRScheduler):
    def __init__(self, max_update, base_lr=0.01, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        assert isinstance(max_update, int)
        if max_update < 1:
            raise MXNetError("maximum number of updates must be strictly positive")
        self.base_lr_orig = base_lr
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = self.max_update - self.warmup_steps

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        if num_update <= self.max_update:
            self.base_lr = self.final_lr + (self.base_lr_orig - self.final_lr) * \
                (1 + math.cos(math.pi * (num_update - self.warmup_steps) /
                              self.max_steps)) / 2
        return self.base_lr
