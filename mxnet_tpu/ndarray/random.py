"""Random samplers with MXNet's global-seed semantics over JAX explicit keys.

Reference: ``python/mxnet/random.py`` + ``src/operator/random/`` +
``src/common/random_generator.*`` (per-device PRNG pools). SURVEY.md §2.1
disposition: "JAX explicit PRNG keys; compat shim for mx.random.seed".

A module-level key is split on every sample — stateful facade, functional
engine. Inside jit traces (hybridized blocks) sampling uses ``next_key()``
captured at trace time; for reproducible jitted dropout use the Gluon layer,
which threads keys explicitly.
"""
from __future__ import annotations

import threading

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .ndarray import NDArray, _put, _dtype_of

__all__ = ["seed", "uniform", "normal", "randn", "randint", "gamma",
           "exponential", "poisson", "shuffle", "multinomial", "bernoulli",
           "negative_binomial", "generalized_negative_binomial",
           "next_key", "current_key", "get_key_data", "set_key_data"]


class _RandState(threading.local):
    def __init__(self):
        self.key = jax.random.key(0)
        self.trace_stack = []   # [(key, counter-box)] while tracing CachedOps


_STATE = _RandState()


def seed(seed_state, ctx="all"):
    """mx.random.seed — reference python/mxnet/random.py."""
    _STATE.key = jax.random.key(int(seed_state))
    from .. import debug as _debug
    if _debug.determinism_enabled():
        # samplers and image augmenters draw from numpy's global RNG; under
        # MXTPU_ENFORCE_DETERMINISM one seed pins the whole input pipeline
        _np.random.seed(int(seed_state) % (2 ** 32))


def next_key():
    """Split a fresh key from the global stream; inside a CachedOp/jit trace
    derive deterministically from the per-call trace key instead (so replays
    get fresh randomness via the key argument, not baked-in constants)."""
    if _STATE.trace_stack:
        key, box = _STATE.trace_stack[-1]
        box[0] += 1
        return jax.random.fold_in(key, box[0])
    _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


class trace_key_scope:
    """Scope used by CachedOp: all next_key() calls derive from this key."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _STATE.trace_stack.append((self._key, [0]))
        return self

    def __exit__(self, *exc):
        _STATE.trace_stack.pop()
        return False


def current_key():
    return _STATE.key


def get_key_data():
    """Serializable uint32 view of the global PRNG key (checkpointing:
    ``mx.checkpoint.CheckpointManager`` snapshots the RNG stream so a
    resumed run replays the exact draws an uninterrupted one makes)."""
    return jax.random.key_data(_STATE.key)


def set_key_data(data):
    """Inverse of :func:`get_key_data`: restore the global PRNG key."""
    _STATE.key = jax.random.wrap_key_data(jnp.asarray(data, jnp.uint32))


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None):
    data = jax.random.uniform(next_key(), _shape(shape),
                              _dtype_of(dtype), low, high)
    return _wrap(data, ctx, out)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    data = loc + scale * jax.random.normal(next_key(), _shape(shape),
                                           _dtype_of(dtype))
    return _wrap(data, ctx, out)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None):
    return normal(loc, scale, shape, dtype, ctx)


def randint(low, high=None, shape=None, dtype="int32", ctx=None, out=None):
    if high is None:
        low, high = 0, low
    data = jax.random.randint(next_key(), _shape(shape), low, high,
                              _dtype_of(dtype) if dtype else jnp.int32)
    return _wrap(data, ctx, out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None):
    data = jax.random.gamma(next_key(), alpha, _shape(shape),
                            _dtype_of(dtype)) * beta
    return _wrap(data, ctx, out)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    data = jax.random.exponential(next_key(), _shape(shape),
                                  _dtype_of(dtype)) * scale
    return _wrap(data, ctx, out)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None):
    data = jax.random.poisson(next_key(), lam, _shape(shape)).astype(
        _dtype_of(dtype))
    return _wrap(data, ctx, out)


def negative_binomial(k=1, p=1, shape=None, dtype=None, ctx=None, out=None):
    """NB(k, p) draws via the Gamma-Poisson mixture (reference
    mx.nd.random.negative_binomial); failures before the k-th success."""
    from .ops import _gamma_poisson   # single home for the mixture math
    data = _gamma_poisson(next_key(), next_key(), float(k),
                          (1.0 - p) / max(p, 1e-12), _shape(shape), dtype)
    return _wrap(data, ctx, out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None):
    """Generalized NB(mean mu, dispersion alpha) — NB with k=1/alpha,
    p=1/(1+mu*alpha) (reference mx.nd.random.generalized_negative_binomial).
    alpha=0 degenerates to Poisson(mu)."""
    from .ops import _gamma_poisson
    a = max(float(alpha), 1e-12)
    data = _gamma_poisson(next_key(), next_key(), 1.0 / a, mu * a,
                          _shape(shape), dtype)
    return _wrap(data, ctx, out)


def bernoulli(p=0.5, shape=None, dtype=None, ctx=None):
    data = jax.random.bernoulli(next_key(), p, _shape(shape)).astype(
        _dtype_of(dtype))
    return _wrap(data, ctx, None)


def multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """Sample from categorical distributions (rows of ``data`` are pmfs).
    Reference: src/operator/random/sample_multinomial_op.cc."""
    n = 1
    if shape:
        n = int(_np.prod(_shape(shape)))
    logits = jnp.log(jnp.maximum(data.data, 1e-37))
    samples = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(n,) + logits.shape[:-1] if logits.ndim > 1
                                     else (n,))
    if logits.ndim > 1:
        samples = jnp.moveaxis(samples, 0, -1)
    if not shape:
        samples = samples.squeeze(-1) if logits.ndim > 1 else samples[0]
    out = NDArray(samples.astype(_dtype_of(dtype)), data.context)
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            samples.astype(jnp.int32).reshape(logits.shape[:-1] + (-1,)),
            axis=-1)
        return out, NDArray(logp, data.context)
    return out


def shuffle(data, **kwargs):
    perm = jax.random.permutation(next_key(), data.shape[0])
    return NDArray(jnp.take(data.data, perm, axis=0), data.context)


def _wrap(data, ctx, out):
    arr = _put(data, ctx)
    if out is not None:
        out._set_data(arr._data)
        return out
    return arr
