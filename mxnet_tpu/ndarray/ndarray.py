"""NDArray: the imperative tensor, wrapping ``jax.Array``.

TPU-native rebuild of the reference NDArray stack (SURVEY.md §2.1):
  - C++ core ``src/ndarray/ndarray.cc`` + ``include/mxnet/ndarray.h``
  - Python surface ``python/mxnet/ndarray/ndarray.py``

Architecture mapping (SURVEY.md §1 "key architectural idea"): in the reference,
every op is pushed to the dependency engine and the Python thread runs ahead;
here JAX/XLA's async dispatch plays that role — ops return immediately with
futures-like ``jax.Array`` values and ``wait_to_read``/``asnumpy`` are the sync
points (``jax.block_until_ready``).

MXNet semantic quirks preserved on purpose (tested against the contract in
tests/test_ndarray.py, modelled on reference tests/python/unittest/test_ndarray.py):
  - default dtype float32
  - in-place ops (``+=``, ``x[:] = v``) mutate the handle; forbidden on arrays
    that an open autograd tape depends on
  - ``reshape`` supports 0 (copy dim) and -1 (infer) codes
  - scalar ops broadcast like mx.nd (numpy-style here; mx.nd was stricter for
    elemwise — we accept the superset, broadcast_* aliases provided)
"""
from __future__ import annotations

import functools
import os
import warnings
import weakref

import numpy as _np
import jax
import jax.numpy as jnp

if os.environ.get("MXTPU_INT64", "") in ("1", "true"):
    # large-tensor mode (reference MXNET_INT64_TENSOR_SIZE build flag):
    # real int64/float64 instead of the 32-bit truncation below
    jax.config.update("jax_enable_x64", True)

#: weak registry of live NDArrays — waitall() blocks on their buffers
#: (reference engine WaitForAll semantics)
_LIVE_ARRAYS = weakref.WeakSet()

from ..base import MXNetError, numeric_types, integer_types
from ..context import Context, current_context, cpu
from .. import _tape
# use-after-donate sentinel (ISSUE 16): stdlib-only import; the host
# access points below gate on its module bool, so MXTPU_DONATION_CHECK=0
# costs one attribute read per access and changes nothing else
from ..lint import donation as _donation

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "concatenate", "stack", "from_jax", "waitall",
           "eye", "linspace"]


def _dtype_of(dtype):
    if dtype is None:
        return jnp.float32
    if dtype == "bfloat16":
        return jnp.bfloat16
    dt = jnp.dtype(dtype)
    # without jax_enable_x64, 64-bit dtypes are narrowed; the warning is
    # value-aware (see _check_narrowing) — numpy makes every Linux int
    # array int64, so warning unconditionally would be pure noise
    if not jax.config.jax_enable_x64:
        if dt == jnp.dtype("int64"):
            return jnp.int32
        if dt == jnp.dtype("float64"):
            return jnp.float32
        if dt == jnp.dtype("uint64"):
            return jnp.uint32
    return dt


def _check_narrowing(np_arr):
    """Warn when 64-bit integer values actually exceed the 32-bit range
    they are about to be narrowed into (reference large-tensor mode:
    MXNET_INT64_TENSOR_SIZE build flag -> MXTPU_INT64=1 here)."""
    if jax.config.jax_enable_x64 or np_arr.size == 0:
        return
    if np_arr.dtype == _np.int64:
        if np_arr.max(initial=0) > 2**31 - 1 or \
                np_arr.min(initial=0) < -2**31:
            warnings.warn(
                "int64 values exceed the int32 range and will wrap; set "
                "MXTPU_INT64=1 for true 64-bit tensors", stacklevel=3)
    elif np_arr.dtype == _np.uint64:
        if np_arr.max(initial=0) > 2**32 - 1:
            warnings.warn(
                "uint64 values exceed the uint32 range and will wrap; set "
                "MXTPU_INT64=1 for true 64-bit tensors", stacklevel=3)


def _ndarray_from_numpy(host):
    """Unpickle target for NDArray.__reduce__ (module-level so pickle can
    resolve it by name; materializes on the unpickler's default device)."""
    import jax.numpy as jnp
    return NDArray(jnp.asarray(host))


class NDArray:
    """An n-dimensional array on a device context.

    Wraps a ``jax.Array`` (``self._data``). Mutation replaces the wrapped
    value — functional underneath, mutable-looking on top (SURVEY.md §7
    design stance).
    """

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_node", "_out_index",
                 "_grad_fresh", "_grad_reduced", "_grad_of", "_grad_hooks",
                 "__weakref__")

    # make NDArray win against numpy array in reflected ops
    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None):
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._grad_req = "null"
        self._grad_fresh = False
        # True once the cross-worker sum ran for the CURRENT accumulated
        # gradient; re-armed whenever autograd writes fresh gradient data
        # (all_reduce_gradients must reduce once per cycle, grad_req='add')
        self._grad_reduced = False
        self._grad_of = None
        # {key: fn} grad-ready hooks (autograd.register_grad_ready_hook);
        # None until the first registration — the common case pays nothing
        self._grad_hooks = None
        self._node = None
        self._out_index = 0
        _LIVE_ARRAYS.add(self)

    def _sync_handles(self):
        """Buffers waitall() must block on (sparse overrides: no densify)."""
        return (self._data,)

    def __reduce__(self):
        """Pickle as host numpy (reference NDArrays pickle via their
        binary blob, python/mxnet/ndarray/ndarray.py __reduce__).  Device
        placement is process-local state: the unpickling process
        re-materializes on ITS default device — which is what DataLoader
        process workers need (host-only children, accelerator parent)."""
        import numpy as _host_np
        return (_ndarray_from_numpy, (_host_np.asarray(self._data),))

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        if _donation._ENABLED:
            _donation.touch(self._data, "shape")
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype) if self._data.dtype != jnp.bfloat16 \
            else self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(_np.prod(self.shape)) if self.shape else 1

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        if self._grad is None:
            return None
        if isinstance(self._grad, NDArray):
            # row_sparse grad (sparse_grad=True path): returned directly,
            # stype preserved for the optimizer's lazy update
            return self._grad
        out = NDArray(self._grad, self._ctx)
        # the wrapper is a live view: in-place mutation of it (clip, scale)
        # writes back to the owner's gradient buffer (see _set_data), so
        # idioms like clip_global_norm([p.grad() ...]) take effect
        out._grad_of = self
        return out

    @property
    def data(self):
        """The underlying jax.Array (TPU-native accessor, not in reference)."""
        return self._data

    # ------------------------------------------------------------------
    # autograd surface (reference: python/mxnet/ndarray/ndarray.py)
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        _tape.mark_variable(self, grad_req, stype=stype)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _tape.backward([self], [out_grad] if out_grad is not None else None,
                       retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        out = NDArray(self._data, self._ctx)
        return out

    def _check_mutable(self):
        if self._node is not None and _tape.is_recording():
            raise MXNetError(
                "in-place mutation of an NDArray produced inside an active "
                "autograd.record() scope is not supported on the TPU rebuild "
                "(the functional tape cannot observe it); use out-of-place "
                "ops or detach() first")

    def _set_data(self, new_data):
        self._check_mutable()
        self._data = new_data
        self._node = None
        self._out_index = 0
        if self._grad_of is not None:
            self._grad_of._grad = new_data

    # ------------------------------------------------------------------
    # conversion & sync points
    # ------------------------------------------------------------------
    def asnumpy(self):
        """Sync point: reference MXNDArraySyncCopyToCPU → WaitForVar."""
        if _donation._ENABLED:
            _donation.touch(self._data, "asnumpy")
        from ..testing import faults as _faults
        _faults.fault_point("ndarray.d2h")
        return _np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        jax.block_until_ready(self._data)
        return self

    def astype(self, dtype, copy=True):
        return _apply1(self, lambda d: d.astype(_dtype_of(dtype)))

    def as_in_context(self, ctx):
        """Device copy: reference CopyFromTo (src/ndarray/ndarray.cc)."""
        ctx = Context(ctx) if not isinstance(ctx, Context) else ctx
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self._data, other._ctx.jax_device))
            return other
        ctx = Context(other) if not isinstance(other, Context) else other
        try:
            dev = ctx.jax_device
            data = jax.device_put(self._data, dev)
        except Exception:
            data = self._data
        out = NDArray(data, ctx)
        # copies stay differentiable (CopyFromTo registers identity grad)
        if _tape.is_recording() and _tape and (self._node is not None
                                               or self._grad_req != "null"):
            outs, node = _tape.apply_op(lambda d: d, [self], name="copyto")
            out._data = outs[0]
            _attach(out, node, 0)
        return out

    def copy(self):
        return self.copyto(self._ctx)

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # shape manipulation
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape"):
            shape = tuple(kwargs["shape"])
        new_shape = _resolve_reshape(self.shape, shape)
        return _apply1(self, lambda d: d.reshape(new_shape), name="reshape")

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def expand_dims(self, axis):
        return _apply1(self, lambda d: jnp.expand_dims(d, axis))

    def squeeze(self, axis=None):
        return _apply1(self, lambda d: jnp.squeeze(d, axis))

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        axes = axes if axes else None
        return _apply1(self, lambda d: jnp.transpose(d, axes))

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        """MXNet Flatten: collapse all but first axis (NOT numpy ravel)."""
        lead = self.shape[0] if self.ndim else 1
        return _apply1(self, lambda d: d.reshape(lead, -1), name="flatten")

    def swapaxes(self, a1, a2):
        return _apply1(self, lambda d: jnp.swapaxes(d, a1, a2))

    def broadcast_to(self, shape):
        shape = tuple(shape)
        cur = self.shape
        if len(cur) < len(shape):
            cur = (1,) * (len(shape) - len(cur)) + cur
        for c, s in zip(cur, shape):
            if c != s and c != 1:
                raise MXNetError(
                    f"cannot broadcast {self.shape} to {shape}")
        return _apply1(self, lambda d: jnp.broadcast_to(
            d.reshape(cur), shape), name="broadcast_to")

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        return _apply1(self, lambda d: jnp.tile(d, reps))

    def repeat(self, repeats, axis=None):
        return _apply1(self, lambda d: jnp.repeat(d, repeats, axis))

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from . import ops as _ops
        return _ops.split(self, num_outputs=num_outputs, axis=axis,
                          squeeze_axis=squeeze_axis)

    # ------------------------------------------------------------------
    # reductions / linalg / misc forwarding (full set in ops.py)
    # ------------------------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.sum(d, axis=_ax(axis),
                                               keepdims=keepdims), name="sum")

    def mean(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.mean(d, axis=_ax(axis),
                                                keepdims=keepdims))

    def max(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.max(d, axis=_ax(axis),
                                               keepdims=keepdims))

    def min(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.min(d, axis=_ax(axis),
                                               keepdims=keepdims))

    def prod(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.prod(d, axis=_ax(axis),
                                                keepdims=keepdims))

    def argmax(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.argmax(d, axis=axis,
                                                  keepdims=keepdims)
                       .astype(jnp.float32))

    def argmin(self, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.argmin(d, axis=axis,
                                                  keepdims=keepdims)
                       .astype(jnp.float32))

    def abs(self):
        return _apply1(self, jnp.abs)

    def sqrt(self):
        return _apply1(self, jnp.sqrt)

    def exp(self):
        return _apply1(self, jnp.exp)

    def log(self):
        return _apply1(self, jnp.log)

    def clip(self, a_min=None, a_max=None):
        return _apply1(self, lambda d: jnp.clip(d, a_min, a_max))

    def dot(self, other):
        from . import ops as _ops
        return _ops.dot(self, other)

    def norm(self, ord=2, axis=None, keepdims=False):
        return _apply1(self, lambda d: jnp.linalg.norm(
            d if d.ndim else d.reshape(1), ord=ord, axis=_ax(axis),
            keepdims=keepdims) if axis is not None else
            jnp.sqrt(jnp.sum(jnp.square(d))) if ord == 2 else
            jnp.sum(jnp.abs(d)), name="norm")

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return _apply1(self, lambda d: jax.nn.one_hot(
            d.astype(jnp.int32), depth) * (on_value - off_value) + off_value)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        from . import ops as _ops
        return _ops.topk(self, axis=axis, k=k, ret_typ=ret_typ,
                         is_ascend=is_ascend)

    def take(self, indices, axis=0, mode="clip"):
        from . import ops as _ops
        return _ops.take(self, indices, axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False):
        from . import ops as _ops
        return _ops.pick(self, index, axis=axis, keepdims=keepdims)

    def slice_axis(self, axis, begin, end):
        from . import ops as _ops
        return _ops.slice_axis(self, axis=axis, begin=begin, end=end)

    def softmax(self, axis=-1):
        return _apply1(self, lambda d: jax.nn.softmax(d, axis=axis))

    def log_softmax(self, axis=-1):
        return _apply1(self, lambda d: jax.nn.log_softmax(d, axis=axis))

    def relu(self):
        return _apply1(self, jax.nn.relu)

    def sigmoid(self):
        return _apply1(self, jax.nn.sigmoid)

    def tanh(self):
        return _apply1(self, jnp.tanh)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def __getitem__(self, key):
        if _donation._ENABLED:
            _donation.touch(self._data, "getitem")
        key = _convert_index(key)
        return _apply1(self, lambda d: d[key], name="getitem")

    def __setitem__(self, key, value):
        self._check_mutable()
        key = _convert_index(key)
        if isinstance(value, NDArray):
            value = value._data
        elif not isinstance(value, (jnp.ndarray, jax.Array)):
            value = jnp.asarray(value, dtype=self._data.dtype)
        self._data = self._data.at[key].set(value)
        self._node = None

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    # ------------------------------------------------------------------
    # arithmetic
    # ------------------------------------------------------------------
    def __add__(self, other):
        return _binary(self, other, jnp.add, name="add")

    __radd__ = __add__

    def __sub__(self, other):
        return _binary(self, other, jnp.subtract, name="sub")

    def __rsub__(self, other):
        return _binary(self, other, lambda a, b: b - a, name="rsub")

    def __mul__(self, other):
        return _binary(self, other, jnp.multiply, name="mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _binary(self, other, jnp.divide, name="div")

    def __rtruediv__(self, other):
        return _binary(self, other, lambda a, b: b / a, name="rdiv")

    def __mod__(self, other):
        # reference mod is C fmod semantics (sign of dividend), not Python %
        return _binary(self, other, jnp.fmod, name="mod")

    def __rmod__(self, other):
        return _binary(self, other, lambda a, b: jnp.fmod(b, a), name="rmod")

    def __pow__(self, other):
        return _binary(self, other, jnp.power, name="pow")

    def __rpow__(self, other):
        return _binary(self, other, lambda a, b: jnp.power(b, a))

    def __neg__(self):
        return _apply1(self, jnp.negative, name="neg")

    def __abs__(self):
        return _apply1(self, jnp.abs)

    def __matmul__(self, other):
        return _binary(self, other, jnp.matmul, name="matmul")

    # in-place: mutate handle (engine-write in the reference)
    def __iadd__(self, other):
        self._set_data(jnp.add(self._data, _raw(other, self)))
        return self

    def __isub__(self, other):
        self._set_data(jnp.subtract(self._data, _raw(other, self)))
        return self

    def __imul__(self, other):
        self._set_data(jnp.multiply(self._data, _raw(other, self)))
        return self

    def __itruediv__(self, other):
        self._set_data(jnp.divide(self._data, _raw(other, self)))
        return self

    # comparisons (return 0/1 float arrays, mx.nd semantics)
    def __eq__(self, other):
        return _binary(self, other,
                       lambda a, b: (a == b).astype(a.dtype
                                                    if jnp.issubdtype(a.dtype, jnp.floating)
                                                    else jnp.float32))

    def __ne__(self, other):
        return _binary(self, other,
                       lambda a, b: (a != b).astype(jnp.float32))

    def __gt__(self, other):
        return _binary(self, other, lambda a, b: (a > b).astype(jnp.float32))

    def __ge__(self, other):
        return _binary(self, other, lambda a, b: (a >= b).astype(jnp.float32))

    def __lt__(self, other):
        return _binary(self, other, lambda a, b: (a < b).astype(jnp.float32))

    def __le__(self, other):
        return _binary(self, other, lambda a, b: (a <= b).astype(jnp.float32))

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("The truth value of an NDArray with multiple "
                         "elements is ambiguous")

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def zeros_like(self):
        return _apply1(self, jnp.zeros_like)

    def ones_like(self):
        return _apply1(self, jnp.ones_like)

    def to_dlpack_for_read(self):
        return jax.dlpack.to_dlpack(self._data)


# ----------------------------------------------------------------------
# dispatch helpers
# ----------------------------------------------------------------------

def _ax(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _attach(out, node, idx):
    if node is not None:
        out._node = node
        out._out_index = idx


def _apply1(x, fn, name=""):
    outs, node = _tape.apply_op(fn, [x], name=name)
    out = NDArray(outs[0], x._ctx)
    _attach(out, node, 0)
    return out


def _raw(other, like):
    if isinstance(other, NDArray):
        return other._data
    if isinstance(other, numeric_types):
        return other
    return jnp.asarray(other, dtype=like._data.dtype)


def _binary(lhs, rhs, fn, name=""):
    if isinstance(rhs, NDArray):
        outs, node = _tape.apply_op(fn, [lhs, rhs], name=name)
        out = NDArray(outs[0], lhs._ctx)
        _attach(out, node, 0)
        return out
    scalar = rhs if isinstance(rhs, numeric_types) else jnp.asarray(rhs)
    outs, node = _tape.apply_op(lambda a: fn(a, scalar), [lhs], name=name)
    out = NDArray(outs[0], lhs._ctx)
    _attach(out, node, 0)
    return out


def apply_nary(fn, inputs, ctx=None, n_out=1, name=""):
    """Public dispatch for ops.py: fn over raw arrays, tape-aware."""
    outs, node = _tape.apply_op(fn, list(inputs), n_out=n_out, name=name)
    ctx = ctx or (inputs[0]._ctx if inputs else current_context())
    results = []
    for i, o in enumerate(outs):
        out = NDArray(o, ctx)
        _attach(out, node, i)
        results.append(out)
    return results[0] if n_out == 1 else results


def _resolve_reshape(cur, shape):
    """MXNet reshape codes, full set. Reference semantics:
    src/operator/tensor/matrix_op-inl.h InferReshapeShape:

      0  copy the corresponding input dim
      -1 infer this dim from the remaining size (at most one)
      -2 copy ALL remaining input dims from the current position
      -3 merge two consecutive input dims into one
      -4 split one input dim into the next TWO target entries (one of
         which may be -1)
    """
    shape = tuple(int(s) for s in shape)
    out = []
    src = 0     # cursor into the input shape
    i = 0
    while i < len(shape):
        s = shape[i]
        if s > 0:
            out.append(s)
            src += 1
        elif s == 0:
            if src >= len(cur):
                raise MXNetError(f"reshape code 0 at dim {i} out of range "
                                 f"for shape {cur}")
            out.append(cur[src])
            src += 1
        elif s == -1:
            if -1 in out:
                raise MXNetError("reshape allows at most one -1 "
                                 f"(outside -4 splits): {shape}")
            out.append(-1)
            src += 1
        elif s == -2:
            out.extend(cur[src:])
            src = len(cur)
        elif s == -3:
            if src + 1 >= len(cur):
                raise MXNetError(f"reshape code -3 at dim {i} needs two "
                                 f"input dims, shape {cur} has "
                                 f"{len(cur) - src} left")
            out.append(cur[src] * cur[src + 1])
            src += 2
        elif s == -4:
            if i + 2 >= len(shape):
                raise MXNetError(
                    f"reshape code -4 must be followed by two split dims: "
                    f"{shape}")
            if src >= len(cur):
                raise MXNetError(f"reshape code -4 at dim {i} out of range "
                                 f"for shape {cur}")
            d = cur[src]
            d1, d2 = shape[i + 1], shape[i + 2]
            d1 = d if d1 == 0 else d1
            d2 = d if d2 == 0 else d2
            if d1 == -1 and d2 == -1:
                raise MXNetError("reshape -4 split cannot infer both dims")
            if d1 == -1:
                d1 = d // d2
            if d2 == -1:
                d2 = d // d1
            if d1 * d2 != d:
                raise MXNetError(f"reshape -4 split {d1}x{d2} != input "
                                 f"dim {d}")
            out.extend([d1, d2])
            src += 1
            i += 2
        else:
            raise MXNetError(f"invalid reshape code {s}")
        i += 1
    total = 1
    for c in cur:
        total *= c
    if -1 in out:
        known = 1
        for o in out:
            if o != -1:
                known *= o
        if known == 0 or total % known:
            raise MXNetError(f"cannot infer -1 in reshape {shape} of {cur}")
        out[out.index(-1)] = total // known
    size = 1
    for o in out:
        size *= o
    if size != total:
        raise MXNetError(f"reshape {shape} of {cur}: target size {size} "
                         f"!= input size {total}")
    return tuple(out)


def _convert_index(key):
    if isinstance(key, NDArray):
        if key._data.dtype == jnp.bool_:
            # boolean-mask indexing (reference NDArray supports it via
            # np-compat semantics): keep the mask a mask — casting it to
            # int32 would silently reinterpret it as integer indices.
            # The result shape is data-dependent (number of True
            # entries), legal eagerly but not under a jit trace.
            import jax.core as _core
            if isinstance(key._data, _core.Tracer):
                raise MXNetError(
                    "boolean-mask indexing has a data-dependent result "
                    "shape and cannot appear inside a jit-traced "
                    "function; use nd.where / contrib.boolean_mask with "
                    "static shapes instead")
            return key._data
        return key._data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_convert_index(k) for k in key)
    return key


# ----------------------------------------------------------------------
# creation functions (reference: python/mxnet/ndarray/ndarray.py +
# src/operator/tensor/init_op.cc)
# ----------------------------------------------------------------------

def _put(data, ctx):
    ctx = Context(ctx) if ctx is not None and not isinstance(ctx, Context) else ctx
    ctx = ctx or current_context()
    try:
        data = jax.device_put(data, ctx.jax_device)
    except Exception:
        pass
    return NDArray(data, ctx)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        data = source_array._data
        if dtype is not None:
            data = data.astype(_dtype_of(dtype))
        return _put(data, ctx)
    is_np_src = isinstance(source_array, _np.ndarray)
    np_arr = _np.asarray(source_array)
    if np_arr.dtype in (_np.int64, _np.uint64):
        _check_narrowing(np_arr)
    if dtype is None:
        # reference semantics (python/mxnet/ndarray/ndarray.py array()):
        # keep the dtype of ndarray sources, default float32 for lists etc.
        if is_np_src and np_arr.dtype != _np.float64:
            dtype = np_arr.dtype
        else:
            dtype = jnp.float32
    return _put(jnp.asarray(np_arr, dtype=_dtype_of(dtype)), ctx)


def from_jax(data, ctx=None):
    return NDArray(data, ctx or current_context())


def zeros(shape, ctx=None, dtype=None):
    shape = (shape,) if isinstance(shape, integer_types) else tuple(shape)
    return _put(jnp.zeros(shape, _dtype_of(dtype)), ctx)


def ones(shape, ctx=None, dtype=None):
    shape = (shape,) if isinstance(shape, integer_types) else tuple(shape)
    return _put(jnp.ones(shape, _dtype_of(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None):
    shape = (shape,) if isinstance(shape, integer_types) else tuple(shape)
    return _put(jnp.full(shape, val, _dtype_of(dtype)), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    data = jnp.arange(start, stop, step, _dtype_of(dtype))
    if repeat > 1:
        data = jnp.repeat(data, repeat)
    return _put(data, ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None):
    return _put(jnp.eye(N, M if M else N, k, dtype=_dtype_of(dtype)), ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    return _put(jnp.linspace(start, stop, num, endpoint=endpoint,
                             dtype=_dtype_of(dtype)), ctx)


def concat(*arrays, dim=1):
    from . import ops as _ops
    return _ops.concat(*arrays, dim=dim)


def concatenate(arrays, axis=0):
    from . import ops as _ops
    return _ops.concat(*arrays, dim=axis)


def stack(*arrays, axis=0):
    from . import ops as _ops
    return _ops.stack(*arrays, axis=axis)


def waitall():
    """Reference: MXNDArrayWaitAll — engine WaitForAll.

    Blocks on every live NDArray's device buffer (weak registry), the
    real equivalent of draining the reference's dependency engine."""
    handles = []
    for arr in list(_LIVE_ARRAYS):
        for h in arr._sync_handles():
            if h is not None and hasattr(h, "block_until_ready"):
                handles.append(h)
    if handles:
        jax.block_until_ready(handles)
